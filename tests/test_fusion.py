"""Pipeline-fusion tier tests (exec/fusion.py).

Covers: fused-vs-unfused result parity (hand-built chains, the SQL
runner, and — under the slow marker — the full TPC-H suite), the
dispatch-counter regression pin (fused Q1 issues >= 2x fewer jit
launches than unfused), segment formation/breaking rules, the
precomputed partition-id path, dictionary cache tokens, and the
kernel-cache counters/capacity knob.
"""

import dataclasses as dc

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import Dictionary, batch_from_pylist
from presto_tpu.config import EngineConfig
from presto_tpu.exec.driver import Pipeline
from presto_tpu.exec.fusion import (
    DFStage, FPStage, FusedSegmentOperatorFactory, fuse_chain,
)
from presto_tpu.exec.operators import (
    FilterProjectOperatorFactory, OutputCollectorFactory,
    TableScanOperatorFactory, ValuesOperatorFactory,
)
from presto_tpu.exec.runner import execute_pipelines
from presto_tpu.expr import build as B
from presto_tpu.localrunner import LocalQueryRunner

from tpch_queries import QUERIES


def _cfg(**kw) -> EngineConfig:
    return dc.replace(EngineConfig(), **kw)


@pytest.fixture(scope="module")
def runner_on():
    return LocalQueryRunner.tpch(scale=0.01)


@pytest.fixture(scope="module")
def runner_off():
    return LocalQueryRunner.tpch(
        scale=0.01, config=_cfg(pipeline_fusion=False))


def _norm(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(v, max(0, 10 - int(np.log10(abs(v))) if v else 10))
            if isinstance(v, float) else v for v in r))
    return sorted(out, key=repr)


def assert_rows_close(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                assert va == pytest.approx(vb, rel=1e-6), (ra, rb)
            else:
                assert va == vb, (ra, rb)


# ---------------------------------------------------------------------------
# hand-built chains
# ---------------------------------------------------------------------------

def _three_stage_chain():
    """values -> filter(a > 2) -> project(a+b, b) -> filter(c < 40) over
    columns a,b — a 3-deep fusable run."""
    batch = batch_from_pylist(
        [T.BIGINT, T.BIGINT],
        [(i, 10 * i) for i in range(8)] + [(None, 3)])
    t2 = (T.BIGINT, T.BIGINT)
    f1 = FilterProjectOperatorFactory(
        B.comparison(">", B.ref(0, T.BIGINT), B.const(2, T.BIGINT)),
        [B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)], list(t2))
    f2 = FilterProjectOperatorFactory(
        None,
        [B.call("add", B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)),
         B.ref(1, T.BIGINT)], list(t2))
    f3 = FilterProjectOperatorFactory(
        B.comparison("<", B.ref(0, T.BIGINT), B.const(40, T.BIGINT)),
        [B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)], list(t2))
    return batch, [f1, f2, f3]


def test_fused_chain_parity():
    batch, fps = _three_stage_chain()
    results = {}
    for fused in (False, True):
        collector = OutputCollectorFactory()
        chain = [ValuesOperatorFactory([batch.to_device()])] + fps
        if fused:
            chain = fuse_chain(chain, _cfg())
            kinds = [type(f).__name__ for f in chain]
            assert kinds == ["ValuesOperatorFactory",
                             "FusedSegmentOperatorFactory"], kinds
        chain = chain + [collector]
        execute_pipelines([Pipeline(chain, name="t")], _cfg())
        results[fused] = sorted(collector.rows())
    assert results[True] == results[False]
    # i=3 survives a>2 and (a+b)=33 < 40; i>=4 give a+b >= 44
    assert results[True] == [(33, 30)]


def test_fuse_chain_rules():
    """Runs < 2 stay unfused unless scan- or partition-adjacent; a
    non-fusable operator breaks the segment."""
    batch, (f1, f2, f3) = _three_stage_chain()
    from presto_tpu.exec.sortop import OrderByOperatorFactory, SortSpec

    sort = OrderByOperatorFactory([SortSpec(0, False, False)])
    chain = fuse_chain([ValuesOperatorFactory([batch]), f1, sort, f2, f3],
                       _cfg())
    kinds = [type(f).__name__ for f in chain]
    # single FP before sort stays; the pair after it fuses
    assert kinds == ["ValuesOperatorFactory", "FilterProjectOperatorFactory",
                     "OrderByOperatorFactory",
                     "FusedSegmentOperatorFactory"], kinds


def test_scan_adjacent_single_stage_fuses():
    """A lone FilterProject directly after a device-staging scan fuses
    (scan coalescing: the ScanFilterAndProjectOperator role) and the scan
    flips to host hand-off."""
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=0.005)
    scan = TableScanOperatorFactory(conn, ["l_quantity"], table="lineitem")
    fp = FilterProjectOperatorFactory(
        None, [B.ref(0, T.DOUBLE)], [T.DOUBLE])
    chain = fuse_chain([scan, fp], _cfg())
    assert isinstance(chain[1], FusedSegmentOperatorFactory)
    assert chain[0].to_device is False
    assert chain[1].coalesce_rows == EngineConfig().scan_batch_rows


def test_fusion_off_reproduces_unfused_chains(runner_off):
    """pipeline_fusion=false leaves lowering byte-identical to the
    pre-fusion engine: no fused segments anywhere."""
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Planner

    plan = optimize(
        Planner(runner_off.metadata).plan(parse_statement(QUERIES[3])),
        runner_off.metadata, runner_off.config)
    phys = PhysicalPlanner(runner_off.registry,
                           runner_off.config).plan(plan)
    for p in phys.pipelines:
        for f in p.factories:
            assert not isinstance(f, FusedSegmentOperatorFactory)
        for f in p.factories:
            if isinstance(f, TableScanOperatorFactory):
                assert f.to_device is True


def test_q3_forms_multi_stage_segments(runner_on):
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Planner

    plan = optimize(
        Planner(runner_on.metadata).plan(parse_statement(QUERIES[3])),
        runner_on.metadata, runner_on.config)
    phys = PhysicalPlanner(runner_on.registry, runner_on.config).plan(plan)
    segments = [f for p in phys.pipelines for f in p.factories
                if isinstance(f, FusedSegmentOperatorFactory)]
    assert segments
    # the probe pipeline carries a dynamic filter + filter/projects in
    # one segment, and the post-join project stack fuses too
    assert any(len(s.stages) >= 2 and isinstance(s.stages[0], DFStage)
               for s in segments)
    assert any(sum(isinstance(st, FPStage) for st in s.stages) >= 2
               for s in segments)


# ---------------------------------------------------------------------------
# SQL-level parity + the dispatch-counter regression pin
# ---------------------------------------------------------------------------

def test_q1_dispatch_reduction(runner_on, runner_off):
    """Fusion must cut the TPC-H Q1 engine path's jit launches by >= 2x
    (the tentpole's measurable claim), with matching results."""
    ra = runner_on.execute(QUERIES[1])
    fused = runner_on._last_task.jit_counters()
    rb = runner_off.execute(QUERIES[1])
    unfused = runner_off._last_task.jit_counters()
    assert_rows_close(ra.rows, rb.rows)
    assert fused["dispatches"] > 0
    assert unfused["dispatches"] >= 2 * fused["dispatches"], (
        fused, unfused)


def test_q6_q3_parity_and_strictly_fewer(runner_on, runner_off):
    for qn in (6, 3):
        ra = runner_on.execute(QUERIES[qn])
        fused = runner_on._last_task.jit_counters()
        rb = runner_off.execute(QUERIES[qn])
        unfused = runner_off._last_task.jit_counters()
        assert_rows_close(ra.rows, rb.rows)
        assert fused["dispatches"] < unfused["dispatches"], (
            qn, fused, unfused)


def test_session_property_toggles_fusion(runner_on):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.execute("set session pipeline_fusion = false")
    r.execute(QUERIES[6])
    off_counters = r._last_task.jit_counters()
    r.execute("set session pipeline_fusion = true")
    r.execute(QUERIES[6])
    on_counters = r._last_task.jit_counters()
    assert on_counters["dispatches"] < off_counters["dispatches"]


def test_explain_analyze_reports_jit_counters(runner_on):
    res = runner_on.execute(
        "explain analyze select count(*) from lineitem where l_quantity > 30")
    text = "\n".join(r[0] for r in res.rows)
    assert "jit disp" in text and "jit dispatches:" in text
    assert "kernel caches" in text


@pytest.mark.slow
@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_fusion_parity(qnum, runner_on, runner_off):
    """Fusion-on vs fusion-off result parity across the full TPC-H
    suite (the conformance oracle separately validates fusion-on against
    sqlite; this pins on==off directly)."""
    ra = runner_on.execute(QUERIES[qnum])
    rb = runner_off.execute(QUERIES[qnum])
    assert ra.column_names == rb.column_names
    assert_rows_close(ra.rows, rb.rows)


# ---------------------------------------------------------------------------
# in-segment partial-aggregation pre-reduce (Fusion II)
# ---------------------------------------------------------------------------

def _agg_chain(aggs, group_channels=(0,)):
    """values -> filter(b < 90) -> HashAgg over a dict key with nulls in
    both the key and the aggregated columns."""
    from presto_tpu.exec.aggregation import HashAggregationOperatorFactory

    rows = []
    for i in range(40):
        key = None if i % 13 == 0 else f"k{i % 3}"
        b = None if i % 7 == 0 else i
        d = None if i % 11 == 0 else float(i) * 1.5
        rows.append((key, b, d))
    batch = batch_from_pylist([T.VARCHAR, T.BIGINT, T.DOUBLE], rows)
    types = [batch.columns[0].type, T.BIGINT, T.DOUBLE]
    fp = FilterProjectOperatorFactory(
        B.comparison("<", B.ref(1, T.BIGINT), B.const(90, T.BIGINT)),
        [B.ref(0, types[0]), B.ref(1, T.BIGINT), B.ref(2, T.DOUBLE)],
        types)
    agg = HashAggregationOperatorFactory(list(group_channels), aggs, types)
    return batch, [fp, agg]


def _run_chain(batch, factories, cfg):
    collector = OutputCollectorFactory()
    chain = fuse_chain(
        [ValuesOperatorFactory([batch.to_device()])] + list(factories),
        cfg)
    execute_pipelines([Pipeline(chain + [collector], name="t")], cfg)
    return chain, sorted(collector.rows(), key=repr)


def test_prereduce_hash_chain_parity():
    """Hand-built chain: the pre-reduced segment + merge aggregation
    must reproduce the unfused aggregation exactly — nullable dict key
    (null group included), sum/count/count(*)/min/max with nulls."""
    from presto_tpu.exec.aggregation import AggChannel
    from presto_tpu.exec.fusion import FusedSegmentOperatorFactory

    aggs = [AggChannel("sum", 1, T.BIGINT),
            AggChannel("count", 1, T.BIGINT),
            AggChannel("count", None, T.BIGINT),
            AggChannel("min", 2, T.DOUBLE),
            AggChannel("max", 2, T.DOUBLE)]
    batch, factories = _agg_chain(aggs)
    chain_on, rows_on = _run_chain(batch, factories, _cfg())
    batch, factories = _agg_chain(aggs)
    chain_off, rows_off = _run_chain(
        batch, factories, _cfg(fusion_partial_agg=False))
    assert rows_on == rows_off
    seg_on = [f for f in chain_on
              if isinstance(f, FusedSegmentOperatorFactory)]
    assert seg_on and seg_on[0].agg_spec is not None
    assert all(f.agg_spec is None for f in chain_off
               if isinstance(f, FusedSegmentOperatorFactory))


def test_prereduce_sort_path_fallback():
    """A dictionary key whose domain exceeds direct_groupby_max_domain
    still pre-reduces (sort path at batch capacity) with exact results."""
    from presto_tpu.exec.aggregation import AggChannel

    aggs = [AggChannel("sum", 1, T.BIGINT),
            AggChannel("count", None, T.BIGINT)]
    batch, factories = _agg_chain(aggs)
    on = _run_chain(batch, factories, _cfg(direct_groupby_max_domain=1))
    batch, factories = _agg_chain(aggs)
    off = _run_chain(batch, factories, _cfg(fusion_partial_agg=False))
    assert on[1] == off[1]


def test_prereduce_global_empty_scan(runner_on):
    """Global pre-reduce over a scan whose filter kills every row: the
    per-batch partial row carries count=0, and the merge produces the
    SQL empty-input defaults (count 0, sum NULL)."""
    res = runner_on.execute(
        "select count(*), sum(l_quantity), min(l_quantity) "
        "from lineitem where l_quantity < 0")
    assert res.rows == [(0, None, None)]


def test_prereduce_global_default_row(runner_on):
    """A global pre-reduce segment that never dispatched (zero input
    batches) still owes its default partial row — COUNT over an empty
    table is 0, not NULL."""
    runner_on.execute(
        "create table memory.fusion_empty_t (x bigint)")
    res = runner_on.execute(
        "select count(*), sum(x), max(x) from memory.fusion_empty_t "
        "where x > 0")
    assert res.rows == [(0, None, None)]
    jc = runner_on._last_task.jit_counters()
    assert jc["prereduce_rows"] == 0


def test_q1_prereduce_dispatch_pin(runner_on):
    """The acceptance pin: TPC-H Q1 at SF0.01 with fusion_partial_agg on
    runs with strictly fewer jit dispatches than PR 3's 5, the scan rows
    fold into in-segment partial states, and the downstream aggregation
    consumes group-sized partials instead of row batches."""
    runner_on.execute(QUERIES[1])
    task = runner_on._last_task
    jc = task.jit_counters()
    assert 0 < jc["dispatches"] < 5, jc
    assert jc["prereduce_rows"] > 50_000, jc
    agg_in = sum(s.input_rows for s in task.operator_stats
                 if "HashAggregation" in s.operator)
    assert 0 < agg_in <= 64, agg_in   # partial states, not 60k rows


def test_q6_prereduce_single_dispatch(runner_on):
    """Q6-class scan->global-agg pipelines collapse to ONE dispatch per
    coalesced batch: at SF0.01 the whole query is a single launch."""
    runner_on.execute(QUERIES[6])
    jc = runner_on._last_task.jit_counters()
    assert jc["dispatches"] == 1, jc
    assert jc["prereduce_rows"] > 50_000, jc


def test_partial_agg_off_restores_pr3_lowering(runner_on):
    """fusion_partial_agg=false must reproduce the PR 3 lowering
    exactly: same factory chain (segment without agg_spec, standard
    aggregation, separate finalize FilterProjects)."""
    from presto_tpu.exec.aggregation import (
        GlobalAggregationOperatorFactory, HashAggregationOperatorFactory,
    )
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Planner

    cfg = _cfg(fusion_partial_agg=False)
    plan = optimize(
        Planner(runner_on.metadata).plan(parse_statement(QUERIES[1])),
        runner_on.metadata, cfg)
    phys = PhysicalPlanner(runner_on.registry, cfg).plan(plan)
    kinds = [type(f).__name__ for f in phys.pipelines[0].factories]
    # the PR 3 shape: a plain segment feeds a standard aggregation, and
    # the two finalize FilterProjects fuse into their own segment
    assert kinds == [
        "TableScanOperatorFactory", "FusedSegmentOperatorFactory",
        "HashAggregationOperatorFactory", "FusedSegmentOperatorFactory",
        "OrderByOperatorFactory", "OutputCollectorFactory"], kinds
    for p in phys.pipelines:
        for f in p.factories:
            if isinstance(f, FusedSegmentOperatorFactory):
                assert f.agg_spec is None
            if isinstance(f, (HashAggregationOperatorFactory,
                              GlobalAggregationOperatorFactory)):
                assert f.post_projections is None


def test_partial_agg_on_q1_lowering(runner_on):
    """With the gate on, Q1's pipeline is scan -> pre-reducing segment
    -> merge aggregation with the finalize projections folded in."""
    from presto_tpu.exec.aggregation import HashAggregationOperatorFactory
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Planner

    plan = optimize(
        Planner(runner_on.metadata).plan(parse_statement(QUERIES[1])),
        runner_on.metadata, runner_on.config)
    phys = PhysicalPlanner(runner_on.registry,
                           runner_on.config).plan(plan)
    chain = phys.pipelines[0].factories
    kinds = [type(f).__name__ for f in chain]
    assert kinds == [
        "TableScanOperatorFactory", "FusedSegmentOperatorFactory",
        "HashAggregationOperatorFactory", "OrderByOperatorFactory",
        "OutputCollectorFactory"], kinds
    seg, agg = chain[1], chain[2]
    assert seg.agg_spec is not None and not seg.agg_spec.global_
    assert "prereduce" in seg.describe()
    assert agg.post_projections and len(agg.post_projections) == 2
    # merge prims re-aggregate the partial states
    assert {a.prim for a in agg.aggs} <= {"sum", "min", "max"}


def test_session_property_toggles_partial_agg():
    r = LocalQueryRunner.tpch(scale=0.01)
    r.execute("set session fusion_partial_agg = false")
    r.execute(QUERIES[6])
    off = r._last_task.jit_counters()
    r.execute("set session fusion_partial_agg = true")
    r.execute(QUERIES[6])
    on = r._last_task.jit_counters()
    assert off["prereduce_rows"] == 0
    assert on["prereduce_rows"] > 0
    assert on["dispatches"] < off["dispatches"]


@pytest.mark.slow
@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_partial_agg_parity(qnum, runner_on):
    """fusion_partial_agg on vs off result parity across the full TPC-H
    suite (partial sums merge in a different association order, so the
    comparison is approximate like the conformance oracle's)."""
    r_off = _PAGG_OFF_RUNNERS.setdefault(
        "tpch", LocalQueryRunner.tpch(
            scale=0.01, config=_cfg(fusion_partial_agg=False)))
    ra = runner_on.execute(QUERIES[qnum])
    rb = r_off.execute(QUERIES[qnum])
    assert ra.column_names == rb.column_names
    assert_rows_close(ra.rows, rb.rows)


_PAGG_OFF_RUNNERS = {}


@pytest.mark.slow
@pytest.mark.parametrize("qnum", sorted(__import__(
    "tpcds_queries").QUERIES))
def test_tpcds_partial_agg_parity(qnum, runner_on):
    """fusion_partial_agg on/off parity across the TPC-DS suite."""
    from tpcds_queries import QUERIES as DSQ

    r_off = _PAGG_OFF_RUNNERS.setdefault(
        "tpcds", LocalQueryRunner.tpch(
            scale=0.003, config=_cfg(fusion_partial_agg=False)))
    r_on = _PAGG_OFF_RUNNERS.setdefault(
        "tpcds_on", LocalQueryRunner.tpch(scale=0.003))
    for r in (r_off, r_on):
        r.metadata.default_catalog = "tpcds"
    ra = r_on.execute(DSQ[qnum])
    rb = r_off.execute(DSQ[qnum])
    assert ra.column_names == rb.column_names
    assert_rows_close(ra.rows, rb.rows)


# ---------------------------------------------------------------------------
# shared dictionary interning (one compile per (table, expr))
# ---------------------------------------------------------------------------

def test_shared_interning_compiles_once():
    """Multi-split scan of one table compiles each unfused expression
    kernel exactly once: every split serves the SAME per-table interning
    dictionaries, so the kernel-cache (token, length) binding is stable
    across splits (pre-PR4: one re-trace per split)."""
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=0.01)
    handle = conn.get_table("customer")
    splits = conn.get_splits(handle, 8)
    assert len(splits) >= 4
    vt = conn.table_schema(handle).column_type("c_name")
    scan = TableScanOperatorFactory(
        conn, ["c_custkey", "c_name", "c_phone"], table="customer")
    fp = FilterProjectOperatorFactory(
        B.comparison(">", B.ref(0, T.BIGINT), B.const(5, T.BIGINT)),
        [B.ref(1, vt), B.ref(2, vt)], [T.BIGINT, vt, vt])
    collector = OutputCollectorFactory()
    cfg = _cfg(pipeline_fusion=False, task_concurrency=1)
    task = execute_pipelines(
        [Pipeline([scan, fp, collector], splits, name="t")], cfg)
    jc = task.jit_counters()
    assert jc["dispatches"] == len(splits)
    assert jc["compiles"] == 1, jc
    assert len(collector.rows()) == 1500 - 5


def test_memory_interning_shares_table_dictionaries():
    """Inserted batches re-code dictionary columns into per-table shared
    interning tables, so multi-batch scans compile once per expression."""
    r = LocalQueryRunner.tpch(
        scale=0.01, config=_cfg(pipeline_fusion=False, task_concurrency=1))
    r.execute("create table memory.interning_t (k bigint, s varchar)")
    for i in range(3):
        r.execute(f"insert into memory.interning_t values "
                  f"({i}, 'v{i}'), ({i + 10}, 'w{i}')")
    conn = r.registry.get("memory")
    dicts = {id(b.columns[1].dictionary)
             for b in conn.tables["interning_t"].batches}
    assert len(dicts) == 1
    res = r.execute("select s from memory.interning_t where k >= 0")
    assert len(res.rows) == 6
    assert r._last_task.jit_counters()["compiles"] == 1


# ---------------------------------------------------------------------------
# partition-id fusion (exchange sink)
# ---------------------------------------------------------------------------

def test_precomputed_partition_matches_eager():
    """A segment feeding a hash-partitioned sink precomputes partition
    ids inside the fused program; the buffers must receive exactly the
    rows the eager hash path routes."""
    from presto_tpu.serde import deserialize_batch
    from presto_tpu.server.buffers import OutputBufferManager
    from presto_tpu.server.exchangeop import PartitionedOutputOperatorFactory

    batch = batch_from_pylist(
        [T.BIGINT, T.VARCHAR],
        [(i, f"k{i % 7}") for i in range(50)])
    fp = FilterProjectOperatorFactory(
        B.comparison("<", B.ref(0, T.BIGINT), B.const(40, T.BIGINT)),
        [B.ref(0, T.BIGINT), B.ref(1, batch.columns[1].type)],
        [T.BIGINT, batch.columns[1].type])

    def run(fuse: bool):
        buffers = OutputBufferManager(4)
        sink = PartitionedOutputOperatorFactory(buffers, [0, 1], 4)
        chain = [ValuesOperatorFactory([batch.to_device()]), fp]
        if fuse:
            chain = fuse_chain(chain + [sink], _cfg())
            assert isinstance(chain[1], FusedSegmentOperatorFactory)
            assert chain[1].partition_spec == ((0, 1), 4)
            assert sink.precomputed is True
        else:
            chain = chain + [sink]
        execute_pipelines([Pipeline(chain, name="t")], _cfg())
        out = {}
        for p in range(4):
            rows = []
            token = 0
            while True:
                pages, token, done = buffers.get_pages(p, token, 100)
                for pg in pages:
                    rows.extend(deserialize_batch(pg).to_pylist())
                if done:
                    break
            out[p] = sorted(rows)
        return out

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# dictionary tokens + kernel cache counters/capacity
# ---------------------------------------------------------------------------

def test_dictionary_tokens_monotonic_and_unique():
    a, b = Dictionary(["x"]), Dictionary(["x"])
    assert a.token != b.token
    assert b.token > a.token
    # tokens never recycle (unlike id()): a new dictionary after GC of an
    # old one still gets a fresh token
    import gc

    old = a.token
    del a
    gc.collect()
    c = Dictionary(["x"])
    assert c.token > old


def test_fp_cache_keys_use_tokens_not_ids():
    import inspect

    from presto_tpu.exec import operators as ops

    src = inspect.getsource(ops.FilterProjectOperator)
    assert "id(c.dictionary)" not in src
    assert "dictionary_binding_key" in src


def test_kernel_cache_counters_and_capacity():
    from presto_tpu import kernelcache as kc

    cache = kc.new_cache("test_cache")
    assert kc.cache_get(cache, ("a",)) is None
    kc.cache_put(cache, ("a",), 1)
    assert kc.cache_get(cache, ("a",)) == 1
    assert cache.hits == 1 and cache.misses == 1
    # explicit capacity evicts LRU-first
    for i in range(5):
        kc.cache_put(cache, ("k", i), i, cap=3)
    assert len(cache) == 3 and cache.evictions >= 2
    stats = kc.cache_stats()["test_cache"]
    assert stats["hits"] == 1 and stats["evictions"] >= 2
    # the EngineConfig knob lands as the process default
    prev = kc.default_capacity()
    try:
        execute_pipelines([], _cfg(kernel_cache_capacity=123))
        assert kc.default_capacity() == 123
    finally:
        kc.set_default_capacity(prev)


def test_task_info_reports_kernel_caches():
    from presto_tpu.kernelcache import cache_stats

    stats = cache_stats()
    assert "filter_project" in stats and "fused_segment" in stats
    for s in stats.values():
        # compiles/compile_ns: per-cache compile-time attribution
        # (kernelcache.record_compile) surfaced alongside hit/miss
        assert set(s) == {"size", "hits", "misses", "evictions",
                          "compiles", "compile_ns"}


# ---------------------------------------------------------------------------
# device-resident hash tier (PR 10): probe-in-segment, FINAL-merge
# fusion, cost-based pre-reduce, and the overflow seam
# ---------------------------------------------------------------------------

def _plan_chains(runner, sql, cfg):
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Planner

    plan = optimize(Planner(runner.metadata).plan(parse_statement(sql)),
                    runner.metadata, cfg)
    return PhysicalPlanner(runner.registry, cfg).plan(plan).pipelines


def test_q3_probe_absorbed_into_segment(runner_on):
    """Q3's probe pipeline runs filter -> project -> probe inside ONE
    fused segment (the filter/project/probe/partial-agg chain of the
    tentpole), and the probe stages name their join type."""
    from presto_tpu.exec.fusion import ProbeStage

    pipelines = _plan_chains(runner_on, QUERIES[3], runner_on.config)
    probes = [s for p in pipelines for f in p.factories
              if isinstance(f, FusedSegmentOperatorFactory)
              for s in f.stages if isinstance(s, ProbeStage)]
    assert len(probes) >= 2
    assert all(s.factory.join_type == "inner" for s in probes)


def test_device_join_probe_off_restores_pr9_lowering(runner_on):
    """device_join_probe=false must reproduce the PR 9 chains exactly:
    no ProbeStage anywhere, probe operators back in the chain, and the
    build side building the sorted index (mode != 'hash')."""
    from presto_tpu.exec.fusion import ProbeStage
    from presto_tpu.exec.joinop import LookupJoinOperatorFactory

    cfg = _cfg(device_join_probe=False)
    pipelines = _plan_chains(runner_on, QUERIES[3], cfg)
    kinds = [type(f).__name__ for p in pipelines for f in p.factories]
    assert "LookupJoinOperatorFactory" in kinds
    for p in pipelines:
        for f in p.factories:
            if isinstance(f, FusedSegmentOperatorFactory):
                assert not any(isinstance(s, ProbeStage)
                               for s in f.stages)
    r = LocalQueryRunner.tpch(scale=0.01, config=cfg)
    r.execute(QUERIES[3])
    join_tiers = [s.kernel_tier for s in r._last_task.operator_stats
                  if s.kernel_tier and ("Build" in s.operator
                                        or "LookupJoin" in s.operator)]
    assert join_tiers and "hash" not in join_tiers


def test_all_new_knobs_off_restores_pr9_chain_shapes(runner_on):
    """The acceptance pin: hash_groupby_enabled=false +
    device_join_probe=false + fusion_final_merge=false (+ the
    cost-based gate off) leaves every lowered chain shaped exactly as
    PR 9 left it, and results match the defaults-on engine."""
    from presto_tpu.exec.fusion import ProbeStage

    cfg = _cfg(hash_groupby_enabled=False, device_join_probe=False,
               fusion_final_merge=False, prereduce_cost_based=False)
    r_off = LocalQueryRunner.tpch(scale=0.01, config=cfg)
    for qn in (1, 3, 6):
        pipelines = _plan_chains(runner_on, QUERIES[qn], cfg)
        for p in pipelines:
            for f in p.factories:
                if isinstance(f, FusedSegmentOperatorFactory):
                    assert not any(isinstance(s, ProbeStage)
                                   for s in f.stages)
        ra = runner_on.execute(QUERIES[qn])
        rb = r_off.execute(QUERIES[qn])
        assert_rows_close(ra.rows, rb.rows)
    # the PR 9 Q1 lowering pin still holds under the off-config
    pipelines = _plan_chains(runner_on, QUERIES[1], cfg)
    kinds = [type(f).__name__ for f in pipelines[0].factories]
    assert kinds == [
        "TableScanOperatorFactory", "FusedSegmentOperatorFactory",
        "HashAggregationOperatorFactory", "OrderByOperatorFactory",
        "OutputCollectorFactory"], kinds


def test_final_merge_fuses_exchange_fed_grouped_merge():
    """A grouped FINAL merge directly on a remote exchange absorbs into
    an empty-stage coalescing segment with the finalize projections
    folded into the merge finish; fusion_final_merge=false restores the
    PR 9 chain exactly."""
    from presto_tpu.exec.aggregation import (
        AggChannel, HashAggregationOperatorFactory,
    )
    from presto_tpu.exec.fusion import fuse_chain
    from presto_tpu.server.exchangeop import ExchangeOperatorFactory

    types = [T.BIGINT, T.DOUBLE, T.BIGINT]
    agg = HashAggregationOperatorFactory(
        [0], [AggChannel("sum", 1, T.DOUBLE),
              AggChannel("sum", 2, T.BIGINT)], types)
    agg.step = "final"
    fin = FilterProjectOperatorFactory(
        None, [B.ref(0, T.BIGINT), B.ref(1, T.DOUBLE)], types)
    exch = ExchangeOperatorFactory(["http://x/v1/task/t/results/0"])
    chain = fuse_chain([exch, agg, fin], _cfg())
    assert isinstance(chain[1], FusedSegmentOperatorFactory)
    assert chain[1].agg_spec is not None
    assert chain[1].coalesce_rows == _cfg().scan_batch_rows
    assert chain[2].post_projections
    off = fuse_chain([exch, agg, fin], _cfg(fusion_final_merge=False))
    assert [type(f).__name__ for f in off] == [
        "ExchangeOperatorFactory", "HashAggregationOperatorFactory",
        "FilterProjectOperatorFactory"]


def test_final_merge_skips_global_merges():
    """Global merge aggregations stay unfused (their empty-input
    default row needs the ORIGINAL prims)."""
    from presto_tpu.exec.aggregation import (
        AggChannel, GlobalAggregationOperatorFactory,
    )
    from presto_tpu.exec.fusion import fuse_chain
    from presto_tpu.server.exchangeop import ExchangeOperatorFactory

    agg = GlobalAggregationOperatorFactory(
        [AggChannel("sum", 0, T.DOUBLE)], [T.DOUBLE])
    agg.step = "final"
    exch = ExchangeOperatorFactory(["http://x/v1/task/t/results/0"])
    chain = fuse_chain([exch, agg], _cfg())
    assert [type(f).__name__ for f in chain] == [
        "ExchangeOperatorFactory", "GlobalAggregationOperatorFactory"]


def test_cost_based_raw_emission_switch():
    """A pre-reducing segment whose observed groups/rows ratio says
    grouping is not reducing flips to raw partial-state emission after
    the first batch — results stay exact, and prereduce_rows stops
    accumulating once flipped."""
    from presto_tpu.exec.aggregation import AggChannel
    from presto_tpu.exec.aggregation import HashAggregationOperatorFactory

    n = 4096
    d = Dictionary([f"k{i}" for i in range(n)])
    vt = None
    rows1 = [(i, float(i)) for i in range(n)]          # all distinct
    rows2 = [(i, float(2 * i)) for i in range(n)]
    from presto_tpu import types as TT
    from presto_tpu.batch import Batch, Column
    import numpy as np

    def mk(rows):
        codes = np.asarray([r[0] for r in rows], np.int32)
        vals = np.asarray([r[1] for r in rows])
        kt = TT.VARCHAR
        return Batch((Column(kt, codes, None, d),
                      Column(TT.DOUBLE, vals)), len(rows))

    types = [mk(rows1).columns[0].type, T.DOUBLE]
    fp = FilterProjectOperatorFactory(
        None, [B.ref(0, types[0]), B.ref(1, T.DOUBLE)], types)
    agg = HashAggregationOperatorFactory(
        [0], [AggChannel("sum", 1, T.DOUBLE),
              AggChannel("count", None, T.BIGINT)], types)

    def run(cfg):
        collector = OutputCollectorFactory()
        chain = fuse_chain(
            [ValuesOperatorFactory([mk(rows1).to_device(),
                                    mk(rows2).to_device()]),
             fp, agg], cfg)
        task = execute_pipelines(
            [Pipeline(chain + [collector], name="t")], cfg)
        return task, sorted(collector.rows())

    cfg_on = _cfg(direct_groupby_max_domain=1 << 14)
    task_on, rows_on = run(cfg_on)
    cfg_off = _cfg(direct_groupby_max_domain=1 << 14,
                   prereduce_cost_based=False)
    task_off, rows_off = run(cfg_off)
    assert rows_on == rows_off
    # with the gate on, only the FIRST batch pre-reduced; off, both did
    assert 0 < task_on.jit_counters()["prereduce_rows"] \
        < task_off.jit_counters()["prereduce_rows"]


def test_hash_groupby_overflow_seam_exact(runner_on):
    """The unfused-fallback seam (satellite): a capacity bucket forced
    to overflow mid-query carries the accumulated on-device state over
    exactly — no double count, no dropped group — and the operator
    reports the seam crossing."""
    sql = ("select l_partkey, sum(l_extendedprice), count(*), "
           "min(l_quantity), max(l_tax) from lineitem group by l_partkey")
    want = runner_on.execute(sql).rows
    r = LocalQueryRunner.tpch(scale=0.01, config=_cfg(
        hash_groupby_init_slots=64, hash_groupby_max_slots=256,
        hash_groupby_min_rows=0))
    got = r.execute(sql).rows
    assert_rows_close(got, want)
    tiers = [s.kernel_tier for s in r._last_task.operator_stats
             if s.kernel_tier]
    assert "hash+sort" in tiers


def test_hash_groupby_tier_engages_on_unbounded_keys(runner_on):
    sql = "select l_partkey, count(*) from lineitem group by l_partkey"
    r = LocalQueryRunner.tpch(scale=0.01,
                              config=_cfg(hash_groupby_min_rows=0))
    ra = r.execute(sql)
    tiers = [s.kernel_tier for s in r._last_task.operator_stats
             if s.kernel_tier]
    assert "hash" in tiers
    r_off = LocalQueryRunner.tpch(
        scale=0.01, config=_cfg(hash_groupby_enabled=False))
    rb = r_off.execute(sql)
    assert_rows_close(ra.rows, rb.rows)
    tiers = [s.kernel_tier for s in r_off._last_task.operator_stats
             if s.kernel_tier]
    assert "hash" not in tiers and "sort" in tiers


def test_session_property_toggles_hash_tier():
    r = LocalQueryRunner.tpch(scale=0.01)
    sql = "select l_partkey, count(*) from lineitem group by l_partkey"
    r.execute("set session hash_groupby_min_rows = 0")
    r.execute("set session hash_groupby_enabled = false")
    r.execute(sql)
    assert not any(s.kernel_tier == "hash"
                   for s in r._last_task.operator_stats)
    r.execute("set session hash_groupby_enabled = true")
    r.execute(sql)
    assert any(s.kernel_tier == "hash"
               for s in r._last_task.operator_stats)


@pytest.mark.slow
@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_hash_tier_parity(qnum, runner_on):
    """All new knobs off vs defaults on: result parity across the full
    TPC-H suite (the per-knob acceptance sweep)."""
    r_off = _PAGG_OFF_RUNNERS.setdefault(
        "pr10_off", LocalQueryRunner.tpch(scale=0.01, config=_cfg(
            hash_groupby_enabled=False, device_join_probe=False,
            fusion_final_merge=False, prereduce_cost_based=False)))
    ra = runner_on.execute(QUERIES[qnum])
    rb = r_off.execute(QUERIES[qnum])
    assert ra.column_names == rb.column_names
    assert_rows_close(ra.rows, rb.rows)


@pytest.mark.slow
@pytest.mark.parametrize("qnum", sorted(__import__(
    "tpcds_queries").QUERIES))
def test_tpcds_hash_tier_parity(qnum):
    """All new knobs off vs defaults on across the TPC-DS suite."""
    from tpcds_queries import QUERIES as DSQ

    r_off = _PAGG_OFF_RUNNERS.setdefault(
        "pr10_ds_off", LocalQueryRunner.tpch(scale=0.003, config=_cfg(
            hash_groupby_enabled=False, device_join_probe=False,
            fusion_final_merge=False, prereduce_cost_based=False)))
    r_on = _PAGG_OFF_RUNNERS.setdefault(
        "pr10_ds_on", LocalQueryRunner.tpch(scale=0.003))
    for r in (r_off, r_on):
        r.metadata.default_catalog = "tpcds"
    ra = r_on.execute(DSQ[qnum])
    rb = r_off.execute(DSQ[qnum])
    assert ra.column_names == rb.column_names
    assert_rows_close(ra.rows, rb.rows)
