"""Geospatial, ML, and Teradata function-pack tests (presto-geospatial
GeoFunctions/BingTileFunctions, presto-ml, presto-teradata-functions)."""

import math

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def one(runner, sql):
    rows = runner.execute("SELECT " + sql).rows
    assert len(rows) == 1
    return rows[0][0]


# --- geospatial -------------------------------------------------------------

def test_st_point_accessors(runner):
    assert one(runner, "ST_Point(1.5, -2)") == "POINT (1.5 -2)"
    assert one(runner, "ST_X(ST_Point(3, 4))") == 3.0
    assert one(runner, "ST_Y(ST_Point(3, 4))") == 4.0
    assert one(runner, "ST_GeometryType(ST_Point(0, 0))") == "ST_Point"


def test_st_area_length(runner):
    sq = "'POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))'"
    assert one(runner, f"ST_Area(ST_GeometryFromText({sq}))") == 16.0
    hole = ("'POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), "
            "(1 1, 2 1, 2 2, 1 2, 1 1))'")
    assert one(runner, f"ST_Area(ST_GeometryFromText({hole}))") == 15.0
    line = "'LINESTRING (0 0, 3 4, 3 8)'"
    assert one(runner, f"ST_Length(ST_GeometryFromText({line}))") == 9.0
    assert one(runner, f"ST_Perimeter(ST_GeometryFromText({sq}))") == 16.0


def test_st_contains_intersects_distance(runner):
    sq = "'POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))'"
    assert one(runner, f"ST_Contains(ST_GeometryFromText({sq}), "
                       "ST_Point(5, 5))") is True
    assert one(runner, f"ST_Contains(ST_GeometryFromText({sq}), "
                       "ST_Point(15, 5))") is False
    # hole excludes
    hole = ("'POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), "
            "(4 4, 6 4, 6 6, 4 6, 4 4))'")
    assert one(runner, f"ST_Contains(ST_GeometryFromText({hole}), "
                       "ST_Point(5, 5))") is False
    assert one(runner, "ST_Intersects(ST_GeometryFromText("
                       "'LINESTRING (0 0, 10 10)'), ST_GeometryFromText("
                       "'LINESTRING (0 10, 10 0)'))") is True
    assert one(runner, "ST_Distance(ST_Point(0, 0), "
                       "ST_Point(3, 4))") == 5.0
    d = one(runner, f"ST_Distance(ST_GeometryFromText({sq}), "
                    "ST_Point(13, 14))")
    assert d == 5.0  # distance to corner (10,10)
    assert one(runner, f"ST_Within(ST_Point(5, 5), "
                       f"ST_GeometryFromText({sq}))") is True


def test_st_misc(runner):
    assert one(runner, "ST_IsValid('POINT (0 0)')") is True
    assert one(runner, "ST_IsValid('NOT WKT')") is False
    env = one(runner, "ST_Envelope(ST_GeometryFromText("
                      "'LINESTRING (1 2, 5 7)'))")
    assert env == "POLYGON ((1 2, 5 2, 5 7, 1 7, 1 2))"
    c = one(runner, "ST_Centroid(ST_GeometryFromText("
                    "'POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))'))")
    assert c == "POINT (1 1)"
    assert one(runner, "ST_NumPoints(ST_GeometryFromText("
                       "'LINESTRING (0 0, 1 1, 2 2)'))") == 3
    area = one(runner, "ST_Area(ST_Buffer(ST_Point(0, 0), 1))")
    assert abs(area - math.pi) < 0.02


def test_spatial_join_via_predicate(runner):
    """Spatial join correctness: points-in-polygons through the join
    path with an ST_Contains predicate (SpatialJoinOperator contract)."""
    runner.execute("CREATE TABLE memory.geoms (name varchar, g varchar)")
    runner.execute(
        "INSERT INTO memory.geoms VALUES "
        "('left',  'POLYGON ((0 0, 5 0, 5 10, 0 10, 0 0))'), "
        "('right', 'POLYGON ((5 0, 10 0, 10 10, 5 10, 5 0))')")
    runner.execute("CREATE TABLE memory.pts (id bigint, x double, "
                   "y double)")
    runner.execute("INSERT INTO memory.pts VALUES "
                   "(1, 1, 1), (2, 7, 3), (3, 3, 9), (4, 12, 1)")
    got = sorted(runner.execute(
        "SELECT p.id, g.name FROM memory.pts p, memory.geoms g "
        "WHERE ST_Contains(g.g, ST_Point(p.x, p.y))").rows)
    assert got == [(1, "left"), (2, "right"), (3, "left")]


def test_bing_tiles(runner):
    qk = one(runner, "bing_tile_at(47.6097, -122.3331, 8)")
    assert isinstance(qk, str) and len(qk) == 8
    assert one(runner, f"bing_tile_zoom_level('{qk}')") == 8
    poly = one(runner, f"bing_tile_polygon('{qk}')")
    assert poly.startswith("POLYGON")
    # the tile polygon contains the original point (lon, lat order)
    assert one(runner, f"ST_Contains('{poly}', "
                       "ST_Point(-122.3331, 47.6097))") is True


# --- ml ---------------------------------------------------------------------

def test_learn_classifier_classify(runner):
    runner.execute("CREATE TABLE memory.iris (label varchar, "
                   "a double, b double)")
    rows = []
    import random

    rnd = random.Random(7)
    for _ in range(60):
        rows.append(f"('low', {rnd.uniform(0,1)}, {rnd.uniform(0,1)})")
        rows.append(f"('high', {rnd.uniform(4,5)}, {rnd.uniform(4,5)})")
    runner.execute("INSERT INTO memory.iris VALUES " + ", ".join(rows))
    got = runner.execute(
        "WITH model AS (SELECT learn_classifier(label, features(a, b)) m "
        "FROM memory.iris) "
        "SELECT classify(features(0.5, 0.5), m), "
        "classify(features(4.5, 4.5), m) FROM model").rows
    assert got == [("low", "high")]


def test_learn_regressor_regress(runner):
    runner.execute("CREATE TABLE memory.lin (y double, x double)")
    vals = ", ".join(f"({3.0 * i + 1.0}, {float(i)})" for i in range(20))
    runner.execute(f"INSERT INTO memory.lin VALUES {vals}")
    got = runner.execute(
        "WITH model AS (SELECT learn_regressor(y, features(x)) m "
        "FROM memory.lin) "
        "SELECT regress(features(10), m) FROM model").rows
    assert got[0][0] == pytest.approx(31.0, abs=1e-3)


# --- teradata ---------------------------------------------------------------

def test_teradata_functions(runner):
    assert one(runner, "index('chip', 'ip')") == 3
    assert one(runner, "index('chip', 'zz')") == 0
    assert one(runner, "char2hexint('AB')") == "00410042"
    assert one(runner, "to_char(DATE '2001-08-22', 'yyyy/mm/dd')") == \
        "2001/08/22"
    import datetime

    assert one(runner, "to_date('1988/04/08', 'yyyy/mm/dd')") == \
        datetime.date(1988, 4, 8)
    assert one(runner,
               "to_timestamp('1988/04/08 2:3:4', 'yyyy/mm/dd hh24:mi:ss')"
               ) == datetime.datetime(1988, 4, 8, 2, 3, 4)


def test_empty_geometries(runner):
    assert one(runner, "ST_Distance('POINT EMPTY', 'POINT (1 1)')") is None
    assert one(runner, "ST_Contains('POLYGON EMPTY', "
                       "ST_Point(0, 0))") is False
    assert one(runner, "ST_Intersects('POINT EMPTY', "
                       "'POINT EMPTY')") is False
    assert one(runner, "ST_Centroid('POINT EMPTY')") is None
    assert one(runner, "ST_Envelope('LINESTRING EMPTY')") is None
    assert one(runner, "ST_Area('POLYGON EMPTY')") == 0.0


def test_empty_geometry_roundtrip(runner):
    assert one(runner, "ST_GeometryFromText('POINT EMPTY')") == \
        "POINT EMPTY"
    assert one(runner, "ST_GeometryFromText('POLYGON EMPTY')") == \
        "POLYGON EMPTY"


def test_spatial_join_uses_grid_index(runner):
    """The Filter(ST_Contains)(cross join) shape lowers to the
    grid-indexed SpatialJoinOperator (SpatialJoinOperator.java:42 +
    PagesRTreeIndex role), not a cartesian product."""
    runner.execute("CREATE TABLE memory.zones (zname varchar, zg varchar)")
    rows = ", ".join(
        f"('z{i}', 'POLYGON (({i*10} 0, {i*10+8} 0, {i*10+8} 8, "
        f"{i*10} 8, {i*10} 0))')" for i in range(20))
    runner.execute(f"INSERT INTO memory.zones VALUES {rows}")
    runner.execute("CREATE TABLE memory.probes (pid bigint, px double, "
                   "py double)")
    pts = ", ".join(f"({i}, {i * 5 + 1}, 4)" for i in range(40))
    runner.execute(f"INSERT INTO memory.probes VALUES {pts}")
    got = sorted(runner.execute(
        "SELECT p.pid, z.zname FROM memory.probes p, memory.zones z "
        "WHERE ST_Contains(z.zg, ST_Point(p.px, p.py))").rows)
    # oracle: point (5i+1, 4) is in zone j iff 10j <= 5i+1 <= 10j+8
    want = sorted(
        (i, f"z{(5 * i + 1) // 10}") for i in range(40)
        if (5 * i + 1) % 10 <= 8 and (5 * i + 1) // 10 < 20)
    assert got == want
    stats = runner._last_task.operator_stats
    assert any("SpatialJoin" in s.operator for s in stats), \
        [s.operator for s in stats]


def test_spatial_distance_join(runner):
    runner.execute("CREATE TABLE memory.sites (sid bigint, sx double, "
                   "sy double)")
    runner.execute("INSERT INTO memory.sites VALUES (1, 0, 0), "
                   "(2, 100, 100), (3, 0.5, 0.5)")
    got = sorted(runner.execute(
        "SELECT a.sid, b.sid FROM memory.sites a, memory.sites b "
        "WHERE ST_Distance(ST_Point(a.sx, a.sy), "
        "ST_Point(b.sx, b.sy)) <= 1.0 AND a.sid < b.sid").rows)
    assert got == [(1, 3)]


def test_spatial_distance_strict_vs_inclusive(runner):
    """ST_Distance < r must exclude pairs at exactly r (the fused plan
    must not widen < to <=)."""
    runner.execute("CREATE TABLE memory.dpts (did bigint, dx double, "
                   "dy double)")
    runner.execute("INSERT INTO memory.dpts VALUES (1, 0, 0), (2, 1, 0)")
    strict = runner.execute(
        "SELECT a.did, b.did FROM memory.dpts a, memory.dpts b "
        "WHERE ST_Distance(ST_Point(a.dx, a.dy), ST_Point(b.dx, b.dy)) "
        "< 1.0 AND a.did < b.did").rows
    assert strict == []
    incl = runner.execute(
        "SELECT a.did, b.did FROM memory.dpts a, memory.dpts b "
        "WHERE ST_Distance(ST_Point(a.dx, a.dy), ST_Point(b.dx, b.dy)) "
        "<= 1.0 AND a.did < b.did").rows
    assert incl == [(1, 2)]
