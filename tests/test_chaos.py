"""Chaos tier: deterministic fault injection against a real in-process
cluster (server/faults.py substrate + server/errortracker.py budgets).

The acceptance proofs for the distributed fault-tolerance layer:

- a retryable transport error on an exchange fetch does NOT fail the
  query (the tracker retries; the token-ack protocol dedups);
- a worker killed mid-query triggers leaf-task reschedule on a
  survivor, consumers are repointed, and the query still returns
  correct rows;
- an exhausted error budget fails the query with the task id AND the
  endpoint in the error message;
- an injected 503 at task create falls over to the next worker (the
  graceful-shutdown race, now driven by the injector);
- ``shutdown_gracefully`` drains under load: buffered output survives
  until consumers fetched it.

Backoff delays here are real but tiny (min 50ms, budget-bounded); the
pure no-wall-clock schedule itself is proven in test_errortracker.py.
"""

import dataclasses
import threading
import time

import pytest

from presto_tpu.client import QueryFailed
from presto_tpu.config import DEFAULT
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.server.faults import FaultInjector

pytestmark = pytest.mark.chaos


def _wait_nodes(co, n, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(co.nodes.alive_nodes()) == n:
            return
        time.sleep(0.02)
    raise AssertionError(f"cluster never reached {n} nodes")


def test_transient_exchange_drop_does_not_fail_query():
    """3 dropped connections on every results fetch: the error tracker
    retries and the query is correct."""
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="fail-n-times",
                 times=3)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={0: inj, 1: inj}) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
    assert len(inj.injections) == 3    # the faults really fired


def test_worker_killed_mid_query_leaf_task_rescheduled():
    """Kill a worker whose results are being withheld: the failure
    detector declares it dead, the scheduler re-creates its leaf task on
    the survivor, the consumer's exchange client is repointed, and the
    query returns the exact count."""
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05)
    inj = FaultInjector()   # victim never serves its result pages
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select count(*) from lineitem").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # wait until tasks are placed on the victim, then kill it
        victim_uri = dqr.workers[1].uri
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and any(u == victim_uri
                          for _, _, u in qs[0]._placements):
                break
            time.sleep(0.02)
        q = list(co.queries.values())[0]
        dqr.kill_worker(1)
        t.join(timeout=60)
        assert not t.is_alive(), "query hung after worker death"
        assert "err" not in res, res
        assert res["rows"] == [(59785,)]   # exact SF0.01 lineitem count
        # the leaf task really moved off the dead worker
        assert all(u != victim_uri for _, _, u in q._placements)


def test_exhausted_budget_fails_with_task_id_and_endpoint():
    """Persistent drops past the error budget: the failure must name the
    fetching task and the producer endpoint, not a bare urllib error."""
    cfg = dataclasses.replace(
        DEFAULT, remote_request_max_error_duration_s=0.2)
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj}) as dqr:
        with pytest.raises(QueryFailed) as ei:
            dqr.execute("select count(*) from nation")
        msg = str(ei.value)
        qid = list(dqr.coordinator.queries)[0]
        assert "exchange fetch" in msg
        assert qid in msg                      # task id ({qid}.{f}.{i})
        assert "/results/" in msg              # the endpoint
        assert "error budget" in msg


def test_injected_503_at_task_create_falls_over():
    """The graceful-shutdown race driven by the injector: the first
    worker answers 503 at task create and the scheduler places the task
    on the next worker instead of failing the query."""
    inj = FaultInjector()
    inj.add_rule(r"^/v1/task/[^/]+$", method="POST", policy="http-503",
                 times=2)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={0: inj}) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        assert [p for _, _, p in inj.injections] == ["http-503"] * 2


def test_unrecoverable_stage_fails_fast_with_context():
    """A dead worker hosting a task WITH remote sources is not
    reschedulable: the query fails promptly, naming the lost task."""
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05)
    inj = FaultInjector()   # only the victim withholds its pages
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                # broadcast join: the probe fragment consumes the
                # broadcast build => a multi-task NON-leaf fragment
                res["rows"] = dqr.execute(
                    "select n_name, count(*) from nation join region "
                    "on n_regionkey = r_regionkey group by n_name").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # kill only after a NON-leaf task (the probe fragment, which
        # consumes the broadcast) landed on the victim — killing earlier
        # would be recovered by the scheduler's create-time fallover
        deadline = time.monotonic() + 10.0
        victim_uri = dqr.workers[1].uri
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and qs[0]._dplan is not None and any(
                    u == victim_uri
                    and qs[0]._dplan.fragments[f].consumed_fragments
                    for f, _, u in qs[0]._placements):
                break
            time.sleep(0.02)
        dqr.kill_worker(1)
        t.join(timeout=60)
        assert not t.is_alive()
        assert "err" in res, res
        msg = str(res["err"])
        assert "not reschedulable" in msg
        assert victim_uri in msg


def test_shutdown_gracefully_drains_under_load():
    """Drain a worker while a query holds undrained output on it: the
    drain must wait for consumers, the query must stay correct, and the
    worker must exit with nothing left buffered."""
    inj = FaultInjector()
    # slow every results fetch so output sits buffered on the worker
    inj.add_rule(r"/results/", method="GET", policy="delay",
                 delay_s=0.15)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={0: inj, 1: inj}) as dqr:
        res = {}

        def run():
            res["rows"] = dqr.execute(
                "select count(*) from lineitem").rows

        t = threading.Thread(target=run)
        t.start()
        victim = dqr.workers[0]
        # wait until the victim actually holds running/undrained tasks
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if victim.task_manager.undrained_count() > 0:
                break
            time.sleep(0.01)
        assert victim.task_manager.undrained_count() > 0
        victim.shutdown_gracefully(drain_timeout_s=15.0)
        # everything buffered was fetched before the server closed
        assert victim.task_manager.undrained_count() == 0
        t.join(timeout=60)
        assert not t.is_alive()
        assert res["rows"] == [(59785,)]
        dqr.workers = dqr.workers[1:]   # victim already closed


def test_cancel_fanout_bounded_and_logged(capsys):
    """A dead node in the cancel fan-out no longer stalls cleanup for
    the full transport budget, and the failure is logged per endpoint
    instead of swallowed."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1) as dqr:
        co = dqr.coordinator
        co.verbose = True
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        # an announced node nobody listens on: DELETE fan-out must fail
        # fast (bounded ~2s budget) and log the endpoint
        co.nodes.announce("ghost", "http://127.0.0.1:9")
        q = list(co.queries.values())[0]
        t0 = time.monotonic()
        q._cancel_worker_tasks()
        assert time.monotonic() - t0 < 10.0
        out = capsys.readouterr().out
        assert "cancel fan-out" in out and "http://127.0.0.1:9" in out


def test_repoint_endpoint_delivered_guard():
    """The worker's remote-sources repoint endpoint refuses to redirect
    a source that already delivered pages (double-count guard)."""
    import json
    import urllib.request

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        co = dqr.coordinator
        q = list(co.queries.values())[0]
        # the gather task consumed its producers: repointing any of them
        # must answer 'delivered' (or the task is already gone: 404)
        gather = [(tid, uri) for fid, tid, uri in q._placements
                  if fid == q._dplan.root_fragment_id][0]
        producer = [(fid, tid, uri) for fid, tid, uri in q._placements
                    if fid != q._dplan.root_fragment_id][0]
        old = f"{producer[2]}/v1/task/{producer[1]}/results/"
        body = json.dumps({"old_prefix": old,
                           "new_prefix": "http://nowhere/results/"}
                          ).encode()
        req = urllib.request.Request(
            f"{gather[1]}/v1/task/{gather[0]}/remote-sources",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            got = json.loads(resp.read())
        assert got["status"] == "delivered"
