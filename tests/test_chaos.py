"""Chaos tier: deterministic fault injection against a real in-process
cluster (server/faults.py substrate + server/errortracker.py budgets).

The acceptance proofs for the distributed fault-tolerance layer:

- a retryable transport error on an exchange fetch does NOT fail the
  query (the tracker retries; the token-ack protocol dedups);
- a worker killed mid-query triggers leaf-task reschedule on a
  survivor, consumers are repointed, and the query still returns
  correct rows;
- an exhausted error budget fails the query with the task id AND the
  endpoint in the error message;
- an injected 503 at task create falls over to the next worker (the
  graceful-shutdown race, now driven by the injector);
- ``shutdown_gracefully`` drains under load: buffered output survives
  until consumers fetched it.

Backoff delays here are real but tiny (min 50ms, budget-bounded); the
pure no-wall-clock schedule itself is proven in test_errortracker.py.
"""

import dataclasses
import threading
import time

import pytest

from presto_tpu.client import QueryFailed
from presto_tpu.config import DEFAULT
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.server.faults import FaultInjector

pytestmark = pytest.mark.chaos


def _wait_nodes(co, n, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(co.nodes.alive_nodes()) == n:
            return
        time.sleep(0.02)
    raise AssertionError(f"cluster never reached {n} nodes")


def test_transient_exchange_drop_does_not_fail_query():
    """3 dropped connections on every results fetch: the error tracker
    retries and the query is correct."""
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="fail-n-times",
                 times=3)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={0: inj, 1: inj}) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
    assert len(inj.injections) == 3    # the faults really fired


def test_worker_killed_mid_query_leaf_task_rescheduled():
    """Kill a worker whose results are being withheld: the failure
    detector declares it dead, the scheduler re-creates its leaf task on
    the survivor, the consumer's exchange client is repointed, and the
    query returns the exact count.  (Pins the PR 5 cascading tier:
    spooling off.)"""
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05,
                              exchange_spooling_enabled=False)
    inj = FaultInjector()   # victim never serves its result pages
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select count(*) from lineitem").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # wait until tasks are placed on the victim, then kill it
        victim_uri = dqr.workers[1].uri
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and any(u == victim_uri
                          for _, _, u in qs[0]._placements):
                break
            time.sleep(0.02)
        q = list(co.queries.values())[0]
        dqr.kill_worker(1)
        t.join(timeout=60)
        assert not t.is_alive(), "query hung after worker death"
        assert "err" not in res, res
        assert res["rows"] == [(59785,)]   # exact SF0.01 lineitem count
        # the leaf task really moved off the dead worker
        assert all(u != victim_uri for _, _, u in q._placements)


def test_exhausted_budget_fails_with_task_id_and_endpoint():
    """Persistent drops past the error budget: the failure must name the
    fetching task and the producer endpoint, not a bare urllib error.
    (Spooling off: with the spooled exchange on, this very scenario is
    RECOVERED instead — the failed-task tick restarts the consumer
    reading from the spool, bypassing the faulted HTTP data plane.)"""
    cfg = dataclasses.replace(
        DEFAULT, remote_request_max_error_duration_s=0.2,
        exchange_spooling_enabled=False)
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj}) as dqr:
        with pytest.raises(QueryFailed) as ei:
            dqr.execute("select count(*) from nation")
        msg = str(ei.value)
        qid = list(dqr.coordinator.queries)[0]
        assert "exchange fetch" in msg
        assert qid in msg                      # task id ({qid}.{f}.{i})
        assert "/results/" in msg              # the endpoint
        assert "error budget" in msg


def test_injected_503_at_task_create_falls_over():
    """The graceful-shutdown race driven by the injector: the first
    worker answers 503 at task create and the scheduler places the task
    on the next worker instead of failing the query."""
    inj = FaultInjector()
    inj.add_rule(r"^/v1/task/[^/]+$", method="POST", policy="http-503",
                 times=2)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={0: inj}) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        assert [p for _, _, p in inj.injections] == ["http-503"] * 2


def _kill_when_nonleaf_placed(dqr, co, victim_idx: int) -> str:
    """Wait until a NON-leaf task (consumes remote sources) lands on the
    victim, then kill it.  Returns the victim uri."""
    victim_uri = dqr.workers[victim_idx].uri
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        qs = list(co.queries.values())
        if qs and qs[0]._dplan is not None and any(
                u == victim_uri
                and qs[0]._dplan.fragments[f].consumed_fragments
                for f, _, u in qs[0]._placements):
            break
        time.sleep(0.02)
    dqr.kill_worker(victim_idx)
    return victim_uri


def _assert_attempt_dedup(q) -> None:
    """Pin the attempt-aware dedup invariant from the live cluster: no
    consumer task consumed pages from TWO attempts of the same producer
    task partition."""
    import re
    import urllib.request

    base_re = re.compile(r"/v1/task/([^/]+?)(a\d+)?/results/(\d+)")
    for _fid, tid, uri in q._placements:
        try:
            with urllib.request.urlopen(f"{uri}/v1/task/{tid}",
                                        timeout=5) as resp:
                import json as _json

                info = _json.loads(resp.read())
        except Exception:  # noqa: BLE001 - worker may be gone
            continue
        consumed_attempts = {}
        for url, stats in (info.get("exchangeSources") or {}).items():
            m = base_re.search(url)
            if m is None or stats.get("consumed", 0) == 0:
                continue
            key = (m.group(1), m.group(3))        # (base task, partition)
            consumed_attempts.setdefault(key, set()).add(m.group(2) or "")
        for key, attempts in consumed_attempts.items():
            assert len(attempts) == 1, (
                f"consumer {tid} mixed attempts {attempts} of "
                f"producer {key}")


def test_worker_killed_nonleaf_stage_retry_exact_rows():
    """PR 5's tentpole, pinned with spooling OFF (the acceptance pin
    that ``exchange_spooling_enabled=false`` restores cascading retry
    exactly): a dead worker owning a NON-leaf task (the probe fragment
    of a broadcast join) no longer fails the query — the recovery
    monitor cancels and re-creates the minimal producer subtree under
    fresh attempt ids, repoints/restarts consumers, and the query
    returns exact oracle rows with no double-counted pages (pinned by
    the attempt-aware dedup counters)."""
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05,
                              exchange_spooling_enabled=False)
    inj = FaultInjector()   # only the victim withholds its pages
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                # broadcast join: the probe fragment consumes the
                # broadcast build => a multi-task NON-leaf fragment
                res["rows"] = dqr.execute(
                    "select n_name, count(*) from nation join region "
                    "on n_regionkey = r_regionkey group by n_name").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        victim_uri = _kill_when_nonleaf_placed(dqr, co, 1)
        q = list(co.queries.values())[0]
        t.join(timeout=120)
        assert not t.is_alive(), "query hung after worker death"
        assert "err" not in res, res
        # exact oracle: every nation joins exactly one region
        assert sorted(res["rows"]) == sorted(
            (n, 1) for n, in dqr.execute(
                "select n_name from nation").rows)
        assert len(res["rows"]) == 25
        assert q.stage_retry_rounds >= 1
        # cascading retry re-ran the producer subtree — the cost the
        # spooled exchange eliminates (contrast: zero in the spooled
        # tests below)
        assert q.producer_reruns_total >= 1
        # the whole subtree moved off the dead worker, on new attempts
        assert all(u != victim_uri for _, _, u in q._placements)
        assert any(tid.rsplit(".", 1)[-1].count("a")
                   for _, tid, _ in q._placements), q._placements
        _assert_attempt_dedup(q)


def test_stage_retry_limit_exhausted_error_context():
    """stage_retry_limit=0 disables whole-stage retry: the same death
    fails the query promptly, naming the stage, the knob, and the lost
    task."""
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05,
                              stage_retry_limit=0,
                              exchange_spooling_enabled=False)
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select n_name, count(*) from nation join region "
                    "on n_regionkey = r_regionkey group by n_name").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        victim_uri = _kill_when_nonleaf_placed(dqr, co, 1)
        t.join(timeout=60)
        assert not t.is_alive()
        assert "err" in res, res
        msg = str(res["err"])
        assert "stage_retry_limit=0" in msg
        assert victim_uri in msg or "stage" in msg


def test_shutdown_gracefully_drains_under_load():
    """Drain a worker while a query holds undrained output on it: the
    drain must wait for consumers, the query must stay correct, and the
    worker must exit with nothing left buffered."""
    inj = FaultInjector()
    # slow every results fetch so output sits buffered on the worker
    inj.add_rule(r"/results/", method="GET", policy="delay",
                 delay_s=0.15)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={0: inj, 1: inj}) as dqr:
        res = {}

        def run():
            res["rows"] = dqr.execute(
                "select count(*) from lineitem").rows

        t = threading.Thread(target=run)
        t.start()
        victim = dqr.workers[0]
        # wait until the victim actually holds running/undrained tasks
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if victim.task_manager.undrained_count() > 0:
                break
            time.sleep(0.01)
        assert victim.task_manager.undrained_count() > 0
        victim.shutdown_gracefully(drain_timeout_s=15.0)
        # everything buffered was fetched before the server closed
        assert victim.task_manager.undrained_count() == 0
        t.join(timeout=60)
        assert not t.is_alive()
        assert res["rows"] == [(59785,)]
        dqr.workers = dqr.workers[1:]   # victim already closed


def test_cancel_fanout_bounded_and_logged(capsys):
    """A dead node in the cancel fan-out no longer stalls cleanup for
    the full transport budget, and the failure is logged per endpoint
    instead of swallowed."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1) as dqr:
        co = dqr.coordinator
        co.verbose = True
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        # an announced node nobody listens on: DELETE fan-out must fail
        # fast (bounded budget) and log the endpoint
        co.nodes.announce("ghost", "http://127.0.0.1:9")
        q = list(co.queries.values())[0]
        t0 = time.monotonic()
        q._cancel_worker_tasks()
        assert time.monotonic() - t0 < 10.0
        out = capsys.readouterr().out
        assert "cancel fan-out" in out and "http://127.0.0.1:9" in out


def test_cancel_fanout_budget_is_a_config_knob(capsys):
    """cancel_fanout_budget_s bounds the per-endpoint fan-out budget:
    a tiny budget fails the dead endpoint well under the old ~2s."""
    cfg = dataclasses.replace(DEFAULT, cancel_fanout_budget_s=0.2)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1,
                                     config=cfg) as dqr:
        co = dqr.coordinator
        co.verbose = True
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        co.nodes.announce("ghost", "http://127.0.0.1:9")
        q = list(co.queries.values())[0]
        q._cfg = cfg
        t0 = time.monotonic()
        q._cancel_worker_tasks()
        elapsed = time.monotonic() - t0
        assert elapsed < 1.5, elapsed   # 0.2s budget, not the 2s default
        out = capsys.readouterr().out
        assert "cancel fan-out" in out and "http://127.0.0.1:9" in out


def test_speculative_clone_beats_straggler_first_finisher_wins():
    """Speculative re-execution: one leaf task's results drain is held
    by the deterministic slow-task fault; its stage peer finishes, the
    lag trips the quantile threshold, a clone lands on the other worker
    under a new attempt id, the consumer is repointed to the clone
    (nothing was consumed from the straggler), and the query returns
    the exact count.  The held original is the loser and is cancelled."""
    cfg = dataclasses.replace(
        DEFAULT, task_recovery_interval_s=0.05,
        speculative_execution_enabled=True,
        speculation_min_runtime_s=0.3, speculation_lag_factor=2.0)
    inj = FaultInjector()
    # hold ONLY task {qid}.0.0's results drain (leaf fragment 0, task 0
    # — placed on worker 0); everything else stays fast
    rule = inj.add_slow_task(r"\.0\.0")
    try:
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=2, config=cfg,
                worker_injectors={0: inj},
                heartbeat_interval_s=0.05) as dqr:
            from presto_tpu.events import EventListener

            class SpecRecorder(EventListener):
                events = []

                def speculation(self, e):
                    self.events.append(e)

            co = dqr.coordinator
            dqr.event_bus.register(SpecRecorder())
            _wait_nodes(co, 2)
            res = {}

            def run():
                try:
                    res["rows"] = dqr.execute(
                        "select count(*) from lineitem").rows
                except Exception as e:  # noqa: BLE001
                    res["err"] = e

            t = threading.Thread(target=run)
            t.start()
            # wait for the clone race to resolve in the clone's favor
            deadline = time.monotonic() + 30.0
            q = None
            won = None
            while time.monotonic() < deadline:
                qs = list(co.queries.values())
                if qs:
                    q = qs[0]
                    won = [sp for sp in q._speculations.values()
                           if sp["state"] == "won"]
                    if won:
                        break
                time.sleep(0.02)
            assert won, (q._speculations if q else "no query")
            # the straggler lost before its pages ever flowed; release
            # the held drain — its late pages must be discarded (stale
            # attempt), not double-counted
            rule.release()
            t.join(timeout=60)
            assert not t.is_alive(), "query hung after speculation"
            assert "err" not in res, res
            assert res["rows"] == [(59785,)]   # exact SF0.01 count
            clone = won[0]["clone"]
            assert clone.endswith("a1")
            assert any(tid == clone for _, tid, _ in q._placements)
            _assert_attempt_dedup(q)
            # the event stream saw the clone spawn AND the race resolve,
            # stamped with the query's trace token (observability PR)
            outcomes = [e.outcome for e in SpecRecorder.events]
            assert "cloned" in outcomes and "won" in outcomes, outcomes
            assert all(e.trace_token == q.trace_token
                       for e in SpecRecorder.events)
            assert SpecRecorder.events[0].clone_id == clone
    finally:
        inj.release_all()


def test_heartbeat_flap_leaves_dead_set_and_skips_recovery():
    """Recovery-monitor flapping: a worker whose heartbeats blip is
    marked dead and then revived by the next successful beat — it must
    leave NodeManager.dead_uris(), and a running query must NOT have
    been recovered off it (the monitor's direct probe confirms the node
    is alive before any cancel/re-create)."""
    # no query in flight: pin the dead-set transition deterministically
    inj = FaultInjector()
    inj.add_rule(r"^/v1/info$", method="GET", policy="drop-connection",
                 times=2)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        victim_uri = dqr.workers[1].uri
        deadline = time.monotonic() + 10.0
        was_dead = False
        while time.monotonic() < deadline:
            if victim_uri in co.nodes.dead_uris():
                was_dead = True
                break
            time.sleep(0.01)
        assert was_dead, "flapped worker never entered dead_uris()"
        # the heartbeat resumes (drops exhausted): it must LEAVE the set
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if victim_uri not in co.nodes.dead_uris():
                break
            time.sleep(0.01)
        assert victim_uri not in co.nodes.dead_uris()
        assert len(co.nodes.alive_nodes()) == 2
        # and it is schedulable again: a query runs green across both
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        q = list(co.queries.values())[0]
        assert q.recovery_rounds == 0


def test_heartbeat_flap_mid_query_no_rerecovery():
    """A heartbeat blip DURING a query must not churn its tasks: the
    monitor's probe sees the worker alive and skips recovery on every
    tick; the query completes exactly on the original placements."""
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05)
    inj = FaultInjector()
    # slow the drain a little so the query is in flight during the flap
    inj.add_rule(r"/results/", method="GET", policy="delay",
                 delay_s=0.1)
    flap = FaultInjector()
    flap.add_rule(r"^/v1/info$", method="GET", policy="drop-connection",
                  times=2)

    class Both:
        def apply_server(self, path, method):
            hit = flap.apply_server(path, method)
            return hit if hit is not None else inj.apply_server(path,
                                                                method)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={0: inj, 1: Both()},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        rows = dqr.execute("select count(*) from lineitem").rows
        assert rows == [(59785,)]
        q = list(co.queries.values())[0]
        assert q.recovery_rounds == 0
        assert q.stage_retry_rounds == 0
        # no task was re-created under a new attempt id
        assert all("a" not in tid.rsplit(".", 1)[-1]
                   for _, tid, _ in q._placements)


# -- TPC-DS on the mesh, chaos-proven (BASELINE.md multi-chip configs) --

def _tpcds_oracle(qn, scale=0.003):
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.localrunner import LocalQueryRunner
    from tests.tpcds_queries import QUERIES

    reg = ConnectorRegistry()
    reg.register("tpcds", TpcdsConnector(scale=scale))
    return LocalQueryRunner(reg, "tpcds").execute(QUERIES[qn]).rows


def _norm(rows):
    return sorted(tuple(round(v, 4) if isinstance(v, float) else v
                        for v in r) for r in rows)


@pytest.mark.slow
@pytest.mark.parametrize("qn", [72, 95])
def test_tpcds_on_mesh_green(qn):
    """ROADMAP #3: the BASELINE.md multi-chip configs (TPC-DS Q72/Q95)
    run on the 2-worker mesh and match the single-process oracle."""
    from tests.tpcds_queries import QUERIES

    want = _tpcds_oracle(qn)
    with DistributedQueryRunner.tpcds(scale=0.003, n_workers=2) as dqr:
        got = dqr.execute(QUERIES[qn]).rows
    assert _norm(got) == _norm(want)


@pytest.mark.slow
@pytest.mark.parametrize("qn", [72, 95])
def test_tpcds_on_mesh_with_transient_faults(qn):
    """Q72/Q95 under injected 503s and delays on exchange fetches: the
    error tracker retries, the token protocol dedups, rows stay exact."""
    from tests.tpcds_queries import QUERIES

    want = _tpcds_oracle(qn)
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="http-503", times=3)
    inj.add_rule(r"/results/", method="GET", policy="delay",
                 delay_s=0.05, times=5)
    with DistributedQueryRunner.tpcds(
            scale=0.003, n_workers=2,
            worker_injectors={0: inj, 1: inj}) as dqr:
        got = dqr.execute(QUERIES[qn]).rows
    assert _norm(got) == _norm(want)
    assert len(inj.injections) >= 3


@pytest.mark.slow
def test_tpcds_q95_worker_kill_stage_retry_exact_rows():
    """The hardest proof: kill a worker running a mid-plan (non-leaf)
    fragment of TPC-DS Q95 on the mesh; whole-stage retry re-creates
    the producer subtree and the single result row (COUNT(DISTINCT) +
    two SUMs — a double-count canary) stays exact."""
    from tests.tpcds_queries import QUERIES

    want = _tpcds_oracle(95)
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05,
                              exchange_spooling_enabled=False)
    inj = FaultInjector()   # victim withholds results => query in flight
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpcds(
            scale=0.003, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(QUERIES[95]).rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        victim_uri = _kill_when_nonleaf_placed(dqr, co, 1)
        q = list(co.queries.values())[0]
        t.join(timeout=300)
        assert not t.is_alive(), "Q95 hung after worker death"
        assert "err" not in res, res
        assert _norm(res["rows"]) == _norm(want)
        assert q.stage_retry_rounds >= 1
        assert all(u != victim_uri for _, _, u in q._placements)
        _assert_attempt_dedup(q)


def test_repoint_endpoint_delivered_guard():
    """The worker's remote-sources repoint endpoint refuses to redirect
    a source that already delivered pages (double-count guard)."""
    import json
    import urllib.request

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        co = dqr.coordinator
        q = list(co.queries.values())[0]
        # the gather task consumed its producers: repointing any of them
        # must answer 'delivered' (or the task is already gone: 404)
        gather = [(tid, uri) for fid, tid, uri in q._placements
                  if fid == q._dplan.root_fragment_id][0]
        producer = [(fid, tid, uri) for fid, tid, uri in q._placements
                    if fid != q._dplan.root_fragment_id][0]
        old = f"{producer[2]}/v1/task/{producer[1]}/results/"
        body = json.dumps({"old_prefix": old,
                           "new_prefix": "http://nowhere/results/"}
                          ).encode()
        req = urllib.request.Request(
            f"{gather[1]}/v1/task/{gather[0]}/remote-sources",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            got = json.loads(resp.read())
        assert got["status"] == "delivered"
