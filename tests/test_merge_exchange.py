"""Distributed ORDER BY via sorted-merge exchange (MergeOperator.java:45
pattern: producers sort their share, the consumer k-way merges) — results
must match the single-process runner exactly, including row order."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.server.dqr import DistributedQueryRunner

pytestmark = pytest.mark.slow



@pytest.fixture(scope="module")
def cluster():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=3) as dqr:
        yield dqr


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=0.01)


def same(cluster, local, sql):
    got = cluster.execute(sql).rows
    want = local.execute(sql).rows
    assert got == want, (len(got), len(want), got[:3], want[:3])
    return got


def test_order_by_scan(cluster, local):
    rows = same(cluster, local,
                "SELECT o_orderkey, o_totalprice FROM orders "
                "ORDER BY o_totalprice DESC, o_orderkey")
    assert len(rows) == 15000
    prices = [p for _, p in rows]
    assert prices == sorted(prices, reverse=True)


def test_order_by_uses_merge_fragments(cluster, local):
    """The plan must actually split into a sorted producer fragment +
    merge consumer (not a single-fragment full sort)."""
    from presto_tpu.server.fragmenter import Fragmenter
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.plan import RemoteMergeNode, SortNode
    from presto_tpu.sql.planner import Metadata, Planner

    md = Metadata(local.registry, "tpch")
    logical = Planner(md).plan(parse_statement(
        "SELECT l_orderkey FROM lineitem ORDER BY l_orderkey"))
    dplan = Fragmenter(metadata=md).fragment(optimize(logical, md))
    root = dplan.fragments[dplan.root_fragment_id].root

    def find(n, cls):
        if isinstance(n, cls):
            return n
        for s in n.sources:
            hit = find(s, cls)
            if hit is not None:
                return hit
        return None

    assert find(root, RemoteMergeNode) is not None
    assert find(root, SortNode) is None  # no consumer-side re-sort
    producer = dplan.fragments[0]
    assert find(producer.root, SortNode) is not None


def test_topn_distributed(cluster, local):
    # tiebreak on orderkey+linenumber: tie order is unspecified (as in
    # the reference), so the test pins a total order
    rows = same(cluster, local,
                "SELECT l_orderkey, l_linenumber, l_extendedprice "
                "FROM lineitem ORDER BY l_extendedprice DESC, "
                "l_orderkey, l_linenumber LIMIT 25")
    assert len(rows) == 25


def test_order_by_after_group_by(cluster, local):
    same(cluster, local,
         "SELECT l_returnflag, l_linestatus, sum(l_quantity) q "
         "FROM lineitem GROUP BY l_returnflag, l_linestatus "
         "ORDER BY l_returnflag, l_linestatus")


def test_order_by_strings_and_nulls(cluster, local):
    same(cluster, local,
         "SELECT c_name, c_nationkey FROM customer "
         "ORDER BY c_name DESC LIMIT 40")
    # nulls via outer join ordering
    same(cluster, local,
         "SELECT o_orderpriority, count(*) c FROM orders "
         "GROUP BY o_orderpriority ORDER BY c DESC, o_orderpriority")


def test_order_by_join(cluster, local):
    same(cluster, local,
         "SELECT c.c_name, o.o_totalprice FROM customer c "
         "JOIN orders o ON c.c_custkey = o.o_custkey "
         "WHERE o.o_totalprice > 300000 "
         "ORDER BY o.o_totalprice DESC, c.c_name LIMIT 50")


def test_inner_limit_not_replicated(cluster, local):
    """An inner LIMIT must not multiply across producer tasks
    (parallel-safety guard on the merge push-down)."""
    rows = same(cluster, local,
                "SELECT o_orderkey FROM "
                "(SELECT o_orderkey FROM orders LIMIT 10) t "
                "ORDER BY o_orderkey")
    assert len(rows) == 10
