"""Plan-shape golden suite: assertPlan-style pins for the CBO and
fragmenter decisions (reference pattern: presto-main/src/test/.../sql/
planner assertPlan fixtures; VERDICT r4 weak #6).

Each test pins ONE decision — join distribution, join order, fragment
count, partial-agg split, scaled-writer sizing, limit/projection
pushdown, transitive predicate inference — so a CBO or fragmenter change
that flips a decision breaks a named test instead of silently shifting
perf."""

import re

import pytest

from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.sql.parser import parse_statement
from presto_tpu.sql.plan import format_plan
from presto_tpu.sql.planner import Metadata, Planner
from presto_tpu.sql.optimizer import optimize


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def logical(runner, sql: str) -> str:
    plan = optimize(Planner(runner.metadata).plan(parse_statement(sql)),
                    runner.metadata)
    return format_plan(plan)


def distributed(runner, sql: str, **cfg_over) -> str:
    import dataclasses as dc

    from presto_tpu.server.fragmenter import Fragmenter

    stmt = parse_statement(sql)
    cfg = dc.replace(runner.session.effective_config(runner.config),
                     **cfg_over)
    plan = optimize(Planner(runner.metadata).plan(stmt),
                    runner.metadata, cfg)
    dplan = Fragmenter(metadata=runner.metadata, config=cfg).fragment(plan)
    lines = []
    for f in dplan.fragments:
        out_kind, out_ch = f.output_partitioning
        lines.append(f"Fragment {f.fragment_id} [{f.partitioning}] "
                     f"=> output {out_kind}"
                     f"{list(out_ch) if out_ch else ''}")
        lines.append(format_plan(f.root))
    return "\n".join(lines)


class TestJoinDecisions:
    def test_q3_join_order_largest_probe_first(self, runner):
        """ReorderJoins pin: lineitem (largest) anchors the left-deep
        chain; customer and orders join into it, never the reverse."""
        sql = """select o_orderdate, sum(l_extendedprice)
                 from customer, orders, lineitem
                 where c_custkey = o_custkey and l_orderkey = o_orderkey
                   and c_mktsegment = 'BUILDING'
                 group by o_orderdate"""
        text = logical(runner, sql)
        scans = re.findall(r"TableScan tpch\.(\w+)", text)
        # depth-first render of a left-deep tree prints the anchor first
        assert scans[0] == "lineitem", text

    def test_small_build_broadcasts(self, runner):
        """DetermineJoinDistributionType pin: nation (25 rows) broadcast
        to the lineitem-side fragment, no hash repartition of lineitem."""
        sql = """select n_name, count(*) from lineitem, supplier, nation
                 where l_suppkey = s_suppkey
                   and s_nationkey = n_nationkey
                 group by n_name"""
        text = distributed(runner, sql)
        assert "broadcast" in text, text

    def test_large_sides_hash_partition(self, runner):
        """Two large relations repartition on the join key instead of
        broadcasting either side."""
        sql = """select count(*) from orders join lineitem
                 on o_orderkey = l_orderkey where o_custkey > 100"""
        # both sides exceed a tightened broadcast limit -> repartition
        text = distributed(runner, sql, broadcast_join_row_limit=100)
        assert re.search(r"output hash\[\d", text), text

    def test_transitive_constant_inference(self, runner):
        """EqualityInference pin: o_orderkey < K infers
        l_orderkey < K through the join equality, so BOTH scans carry
        the constant filter."""
        sql = """select count(*) from orders, lineitem
                 where l_orderkey = o_orderkey and o_orderkey < 1000"""
        text = logical(runner, sql)
        assert len(re.findall(r"lt\(.*1000", text)) >= 2, text


class TestAggregationDecisions:
    def test_q1_partial_final_split(self, runner):
        """Partial aggregation runs in the scan fragment; the final
        merge runs after the hash exchange on the group keys."""
        sql = """select l_returnflag, count(*), sum(l_quantity)
                 from lineitem group by l_returnflag"""
        text = distributed(runner, sql)
        assert "step=partial" in text and "step=final" in text, text

    def test_partial_agg_through_union(self, runner):
        """PushPartialAggregationThroughUnion pin: each UNION ALL branch
        pre-aggregates; one final merge above the union."""
        sql = """select k, sum(v) from (
                   select l_linenumber k, l_quantity v from lineitem
                   union all
                   select o_shippriority k, o_totalprice v from orders
                 ) t group by k"""
        text = logical(runner, sql)
        assert text.count("step=partial") == 2, text
        assert text.count("step=final") == 1, text

    def test_distinct_agg_rewrites_two_level(self, runner):
        """count(DISTINCT x) pins to the two-level rewrite: an inner
        keys=[group, x] dedup aggregation under the outer count
        (SingleDistinctAggregationToGroupBy role) — no /distinct marker
        survives into the physical plan."""
        sql = """select l_suppkey, count(distinct l_partkey)
                 from lineitem group by l_suppkey"""
        text = logical(runner, sql)
        assert "/distinct" not in text, text
        assert len(re.findall(r"Aggregation keys=\[0, 1\]", text)) == 1, \
            text


class TestLimitAndProjectionDecisions:
    def test_limit_through_union_branches(self, runner):
        sql = """select l_orderkey from lineitem
                 union all select o_orderkey from orders limit 7"""
        text = logical(runner, sql)
        # limit appears above the union AND inside each branch
        assert text.count("Limit 7") >= 3, text

    def test_projection_computes_below_join(self, runner):
        """PushProjectionThroughJoin pin: the arithmetic over lineitem
        columns evaluates below the join (in the scan-side project),
        not above it."""
        sql = """select o_orderdate,
                        l_extendedprice * (1 - l_discount) as rev
                 from orders join lineitem on o_orderkey = l_orderkey"""
        text = logical(runner, sql)
        lines = text.splitlines()
        join_depth = next(i for i, ln in enumerate(lines) if "Join" in ln)
        mul_line = next(i for i, ln in enumerate(lines)
                        if "multiply" in ln)
        assert mul_line > join_depth, text

    def test_sorted_limit_merges_single_fragment(self, runner):
        """ORDER BY + LIMIT: per-task TopN under a merge/single gather
        (MergingOutput role) — exactly one single-partition fragment."""
        sql = """select l_orderkey, l_extendedprice from lineitem
                 order by l_extendedprice desc limit 5"""
        text = distributed(runner, sql)
        assert len(re.findall(r"Fragment \d+ \[single\]", text)) == 1, text


class TestMemoDecisions:
    """Memo/CBO pins (sql/memo.py): the q72-class multi-join where
    bounded bushy enumeration beats the greedy left-deep orderer, the
    cost-chosen distribution annotation, and the memo-off restore."""

    Q72_CLASS = """select count(*)
                   from lineitem, orders, customer, supplier, nation
                   where l_orderkey = o_orderkey
                     and o_custkey = c_custkey
                     and c_nationkey = n_nationkey
                     and l_suppkey = s_suppkey
                     and n_name = 'CHINA'"""

    @staticmethod
    def _joins(plan):
        from presto_tpu.sql.plan import JoinNode

        out = []

        def walk(n):
            if isinstance(n, JoinNode):
                out.append(n)
            for s in n.sources:
                walk(s)

        walk(plan)
        return out

    def _optimized(self, runner, **cfg_over):
        import dataclasses as dc

        cfg = dc.replace(runner.session.effective_config(runner.config),
                         **cfg_over)
        return optimize(Planner(runner.metadata).plan(
            parse_statement(self.Q72_CLASS)), runner.metadata, cfg)

    def test_memo_picks_bushy_build_side(self, runner):
        """Memo pin: the dimension chain orders->customer->nation builds
        as its OWN join subtree (bushy) — the right (build) child of some
        join is itself a join, a shape the greedy left-deep orderer can
        never produce."""
        from presto_tpu.sql.plan import JoinNode

        plan = self._optimized(runner)
        joins = self._joins(plan)
        assert any(isinstance(j.right, JoinNode) for j in joins), \
            format_plan(plan)
        # lineitem still anchors the probe side (largest relation)
        scans = re.findall(r"TableScan tpch\.(\w+)", format_plan(plan))
        assert scans[0] == "lineitem", scans

    def test_memo_annotates_cost_chosen_distribution(self, runner):
        """Every keyed join in the memo plan carries its cost-chosen
        distribution; small builds replicate."""
        joins = self._joins(self._optimized(runner))
        assert joins and all(j.distribution is not None for j in joins)
        assert any(j.distribution == "replicated" for j in joins)

    def test_memo_off_restores_left_deep_greedy(self, runner):
        """optimizer_use_memo=false restores the greedy plans exactly:
        strictly left-deep (no join ever builds against a join subtree),
        no distribution annotations."""
        from presto_tpu.sql.plan import JoinNode

        plan = self._optimized(runner, optimizer_use_memo=False)
        joins = self._joins(plan)
        assert joins and all(not isinstance(j.right, JoinNode)
                             for j in joins), format_plan(plan)
        assert all(j.distribution is None for j in joins)
        scans = re.findall(r"TableScan tpch\.(\w+)", format_plan(plan))
        assert scans[0] == "lineitem", scans

    def test_memo_and_greedy_value_parity(self, runner):
        on = runner.execute(self.Q72_CLASS).rows
        runner.execute("set session optimizer_use_memo = false")
        off = runner.execute(self.Q72_CLASS).rows
        runner.execute("reset session optimizer_use_memo")
        assert on == off

    def test_memo_distribution_respects_broadcast_cap(self, runner):
        """Tightening the broadcast cap flips the memo's choice to
        PARTITIONED and the fragmenter emits hash exchanges."""
        sql = """select count(*) from orders join lineitem
                 on o_orderkey = l_orderkey where o_custkey > 100"""
        text = distributed(runner, sql, broadcast_join_row_limit=100)
        assert re.search(r"output hash\[\d", text), text
        text = distributed(runner, sql)
        assert "dist=replicated" in text or "broadcast" in text, text


class TestWriterDecisions:
    def test_scaled_writer_fragment(self, runner):
        """INSERT plans a 'scaled' writer fragment sized by estimated
        input volume (ScaledWriterScheduler role)."""
        import dataclasses as dc

        from presto_tpu import types as T
        from presto_tpu.config import DEFAULT
        from presto_tpu.server.fragmenter import Fragmenter
        from presto_tpu.sql.plan import (
            OutputNode, TableFinishNode, TableWriterNode,
        )

        stmt = parse_statement(
            "select l_orderkey, l_extendedprice from lineitem")
        plan = optimize(Planner(runner.metadata).plan(stmt),
                        runner.metadata)
        wcols = (("rows", T.BIGINT), ("fragment", T.VARCHAR))
        fcols = (("rows", T.BIGINT),)
        writer = TableWriterNode(plan.source, "memory", "tgt", 0, wcols)
        root = OutputNode(
            TableFinishNode(writer, "memory", "tgt", 0, fcols), fcols)
        cfg = dc.replace(DEFAULT, scaled_writer_rows_per_task=10_000)
        dplan = Fragmenter(metadata=runner.metadata,
                           config=cfg).fragment(root)
        scaled = [f for f in dplan.fragments
                  if f.partitioning == "scaled"]
        assert scaled and scaled[0].scale_rows is not None, [
            (f.fragment_id, f.partitioning) for f in dplan.fragments]
