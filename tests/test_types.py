"""Type system unit tests (reference tier: presto-spi type tests)."""

import decimal

import numpy as np
import pytest

from presto_tpu import types as T


def test_simple_dtypes():
    assert T.BIGINT.np_dtype == np.dtype("int64")
    assert T.INTEGER.np_dtype == np.dtype("int32")
    assert T.DOUBLE.np_dtype == np.dtype("float64")
    assert T.BOOLEAN.np_dtype == np.dtype("bool_")
    assert T.DATE.np_dtype == np.dtype("int32")
    assert T.VARCHAR.np_dtype == np.dtype("int32")
    assert T.VARCHAR.is_dictionary


def test_decimal_roundtrip():
    d = T.DecimalType("decimal", precision=15, scale=2)
    assert d.from_python("12.34") == 1234
    assert d.from_python("12.345") == 1235  # half-up
    assert d.to_python(1234) == decimal.Decimal("12.34")
    assert d.display() == "decimal(15,2)"


def test_date_roundtrip():
    import datetime

    assert T.DATE.from_python("1995-01-01") == 9131
    assert T.DATE.to_python(9131) == datetime.date(1995, 1, 1)


def test_parse_type():
    assert T.parse_type("bigint") is T.BIGINT
    assert T.parse_type("decimal(15,2)") == T.DecimalType("decimal", 15, 2)
    assert T.parse_type("varchar(25)") == T.VarcharType("varchar", 25)
    assert T.parse_type("double") is T.DOUBLE
    with pytest.raises(ValueError):
        T.parse_type("frobnicate")


def test_common_super_type():
    assert T.common_super_type(T.INTEGER, T.BIGINT) is T.BIGINT
    assert T.common_super_type(T.BIGINT, T.DOUBLE) is T.DOUBLE
    assert T.common_super_type(T.UNKNOWN, T.DATE) is T.DATE
    d1 = T.DecimalType("decimal", 15, 2)
    assert T.common_super_type(d1, T.BIGINT) == T.DecimalType("decimal", 21, 2)
    assert T.common_super_type(
        T.VarcharType("varchar", 5), T.VarcharType("varchar", 9)
    ) == T.VarcharType("varchar", 9)
    assert T.common_super_type(T.DATE, T.TIMESTAMP) is T.TIMESTAMP
    assert T.common_super_type(T.BOOLEAN, T.BIGINT) is None
