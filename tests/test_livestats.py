"""Live query telemetry (PR 9): in-flight progress sampling, the timed
span tree, compile-time attribution, the slow-query log, and the
latency-histogram /metrics plane.

The acceptance pins:

- a mid-query poll OBSERVES progress: with a fault-injected slow task
  holding the root drain, /v1/query/{id}/timeseries and the
  client-protocol ``stats`` object show monotonically increasing
  completed-split/row counts while the query is still RUNNING;
- ``stats_sampling_enabled=false`` restores PR 8's single post-drain
  collection exactly (no samples, no progress object, rollup only
  after the drain);
- the span tree round-trips: /v1/query/{id}/spans and the query.json
  QueryCompletedEvent carry the same tree, every stage/task span nests
  inside the query span with end >= start;
- EXPLAIN ANALYZE (both tiers) shows the compile-vs-execute split and
  the hot-operator footer.
"""

import json
import logging
import re
import threading
import time
import urllib.request

import pytest

from presto_tpu.config import EngineConfig
from presto_tpu.server.faults import FaultInjector


def _fetch(uri: str):
    with urllib.request.urlopen(uri, timeout=10) as resp:
        return json.loads(resp.read())


def _cfg(**kw) -> EngineConfig:
    return EngineConfig(**kw)


def _run_async(client, sql):
    out = {}

    def run():
        try:
            out["rows"] = client.execute(sql)[1]
        except Exception as e:  # noqa: BLE001
            out["err"] = e

    t = threading.Thread(target=run)
    t.start()
    return t, out


GROUP_SQL = ("select l_returnflag, count(*), sum(l_extendedprice) "
             "from lineitem group by l_returnflag")


class TestLiveSampling:
    def test_midquery_poll_observes_progress(self):
        """The headline acceptance: >= 2 RUNNING samples with
        monotonically increasing completed-split and row counts, both
        on the timeseries endpoint and the client-protocol stats
        object, BEFORE the query finishes."""
        inj = FaultInjector()
        # hold the root task's result drain: leaves finish over time,
        # the root finishes producing, but the drain cannot complete —
        # the query stays RUNNING while real progress accumulates
        rule = inj.add_slow_task(r"\.1\.0")
        from presto_tpu.server.dqr import DistributedQueryRunner

        cfg = _cfg(stats_sample_interval_s=0.05)
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=2, config=cfg,
                worker_injectors={0: inj, 1: inj}) as dqr:
            client = dqr.new_client()
            t, out = _run_async(client, GROUP_SQL)
            co_uri = dqr.coordinator.uri
            polls = []
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                qid = client.last_query_id
                if qid:
                    ts = _fetch(f"{co_uri}/v1/query/{qid}/timeseries")
                    if ts["state"] not in ("RUNNING",):
                        if ts["state"] in ("FINISHED", "FAILED"):
                            break
                    polls.append(ts)
                    running = [s for s in ts["samples"]
                               if s["state"] == "RUNNING"]
                    # stop once progress moved while still RUNNING and
                    # the CLIENT's own polling also caught a RUNNING
                    # progress object — releasing the hold on the
                    # timeseries condition alone raced the client's
                    # poll cadence under full-suite load (the client
                    # thread may not have landed a progress-carrying
                    # poll yet)
                    client_saw = any(
                        s.get("state") == "RUNNING"
                        and "completedSplits" in s
                        for s in client.stats_history)
                    if (len(running) >= 2
                            and running[-1]["splits_completed"]
                            > running[0]["splits_completed"]
                            and client_saw):
                        break
                time.sleep(0.05)
            rule.release()
            t.join(timeout=30)
            assert "err" not in out, out.get("err")
            assert polls, "no mid-query timeseries polls landed"
            samples = polls[-1]["samples"]
            running = [s for s in samples if s["state"] == "RUNNING"]
            # >= 2 samples observed while the query was RUNNING
            assert len(running) >= 2
            completed = [s["splits_completed"] for s in running]
            rows = [s["output_rows"] for s in running]
            # monotonic non-decreasing, strictly increasing overall
            assert completed == sorted(completed)
            assert rows == sorted(rows)
            assert completed[-1] > completed[0]
            assert rows[-1] >= rows[0] > 0
            assert all(s["splits_total"] == 3 for s in running)
            # the client-protocol stats object carried the same
            # progress shape mid-query (StatementStats role)
            live = [s for s in client.stats_history
                    if s.get("state") == "RUNNING"
                    and "completedSplits" in s]
            assert live, "no RUNNING poll carried split accounting"
            assert live[-1]["totalSplits"] == 3
            assert live[-1]["processedRows"] > 0
            assert 0.0 <= live[-1]["progressPercent"] <= 100.0
            # the final payload reports 100% with every split done
            done = client.stats_history[-1]
            assert done["state"] == "FINISHED"
            assert done["completedSplits"] == done["totalSplits"] == 3
            assert done["progressPercent"] == 100.0

    def test_sampling_disabled_restores_single_collection(self):
        """stats_sampling_enabled=false: NO samples, NO progress object
        on any poll, and the stage rollup appears only after the drain
        — PR 8's single post-drain collection, exactly."""
        inj = FaultInjector()
        rule = inj.add_slow_task(r"\.1\.0")
        from presto_tpu.server.dqr import DistributedQueryRunner

        cfg = _cfg(stats_sampling_enabled=False)
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=2, config=cfg,
                worker_injectors={0: inj, 1: inj}) as dqr:
            client = dqr.new_client()
            t, out = _run_async(client, GROUP_SQL)
            co_uri = dqr.coordinator.uri
            saw_running = False
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                qid = client.last_query_id
                if qid:
                    detail = _fetch(f"{co_uri}/v1/query/{qid}")
                    if detail["state"] == "RUNNING":
                        saw_running = True
                        # mid-query: no sampler, so no rollup yet
                        assert detail["stageStats"] == {}
                        assert detail["progress"] == {}
                        ts = _fetch(
                            f"{co_uri}/v1/query/{qid}/timeseries")
                        assert ts["samples"] == []
                        break
                time.sleep(0.05)
            rule.release()
            t.join(timeout=30)
            assert "err" not in out, out.get("err")
            assert saw_running, "never observed the query RUNNING"
            qid = client.last_query_id
            ts = _fetch(f"{co_uri}/v1/query/{qid}/timeseries")
            assert ts["samples"] == []   # still none after the drain
            # the post-drain collection still fed the rollup surfaces
            detail = _fetch(f"{co_uri}/v1/query/{qid}")
            assert detail["stageStats"]
            # and no client poll ever carried split accounting
            assert all("completedSplits" not in s
                       for s in client.stats_history)

    def test_runtime_tasks_live_midquery(self):
        """Satellite regression: a mid-query SELECT over
        system.runtime.tasks sees current (monotonically non-decreasing,
        non-zero) rows fed from the live sampler, not a frozen
        post-drain rollup."""
        inj = FaultInjector()
        rule = inj.add_slow_task(r"\.1\.0")
        from presto_tpu.server.dqr import DistributedQueryRunner

        cfg = _cfg(stats_sample_interval_s=0.05)
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=2, config=cfg,
                worker_injectors={0: inj, 1: inj}) as dqr:
            client = dqr.new_client()
            t, out = _run_async(client, GROUP_SQL)
            poller = dqr.new_client()
            polls = []
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and len(polls) < 3:
                qid = client.last_query_id
                if not qid:
                    time.sleep(0.02)
                    continue
                _, data = poller.execute(
                    "select task_id, state, output_rows, elapsed_s "
                    "from system.runtime.tasks")
                mine = [r for r in data if r[0].startswith(qid + ".")]
                state = _fetch(f"{dqr.coordinator.uri}/v1/query/{qid}"
                               )["state"]
                if state != "RUNNING":
                    if state in ("FINISHED", "FAILED"):
                        break
                    continue
                if mine:
                    polls.append(mine)
                time.sleep(0.1)
            rule.release()
            t.join(timeout=30)
            assert "err" not in out, out.get("err")
            assert len(polls) >= 2, "needed >= 2 mid-query polls"
            totals = [sum(r[2] for r in p) for p in polls]
            # non-zero and monotonic non-decreasing across polls
            assert totals[0] > 0
            assert totals == sorted(totals)
            # elapsed_s reported and growing for the held root task
            elapsed = [max(r[3] for r in p) for p in polls]
            assert elapsed[-1] >= elapsed[0] > 0

    def test_runtime_queries_progress_columns(self):
        from presto_tpu.server.dqr import DistributedQueryRunner

        with DistributedQueryRunner.tpch(scale=0.002,
                                         n_workers=2) as dqr:
            dqr.execute("select count(*) from lineitem")
            data = dqr.execute(
                "select query_id, state, completed_splits, "
                "total_splits, progress_percent "
                "from system.runtime.queries "
                "where state = 'FINISHED'").rows
            assert data
            # finished queries report full split accounting
            assert any(r[2] == r[3] and r[3] > 0 and r[4] == 100.0
                       for r in data)


class TestSpans:
    def test_span_tree_roundtrips_and_nests(self, tmp_path):
        """/v1/query/{id}/spans == the query.json event's tree; every
        stage/task-attempt span nests inside the query span with
        end >= start; the profile tool replays it."""
        from presto_tpu.server.dqr import DistributedQueryRunner
        from presto_tpu.spans import validate_span_tree

        log = str(tmp_path / "query.json")
        with DistributedQueryRunner.tpch(scale=0.002, n_workers=2,
                                         event_log_path=log) as dqr:
            dqr.execute(GROUP_SQL)
            q = list(dqr.coordinator.queries.values())[-1]
            tree = _fetch(
                f"{dqr.coordinator.uri}/v1/query/{q.query_id}/spans")
        events = [json.loads(line) for line in
                  open(log, encoding="utf-8")]
        completed = [e for e in events
                     if e["event"] == "QueryCompletedEvent"]
        assert completed and completed[-1]["spans"]
        # round-trip: the event carries the SAME tree the endpoint
        # served (both JSON round-trips of one build)
        assert completed[-1]["spans"] == tree
        assert validate_span_tree(tree) == []
        kinds = {c["kind"] for c in tree["children"]}
        assert {"phase", "stage"} <= kinds
        names = {c["name"] for c in tree["children"]}
        # coordinator phases recorded from its own timestamps
        assert {"parse", "analyze", "optimize", "fragment",
                "schedule", "execute"} <= names
        stages = [c for c in tree["children"] if c["kind"] == "stage"]
        assert len(stages) == 2   # leaf + final agg fragments
        for st in stages:
            assert st["children"], "stage span without task spans"
            for task in st["children"]:
                assert task["kind"] == "task"
                assert task["end"] >= task["start"]
                assert task["attributes"]["attempt"] == 0
        # every span carries the query's trace token as trace id
        assert tree["traceToken"] == q.trace_token
        assert all(c["traceToken"] == q.trace_token
                   for c in tree["children"])

    def test_distributed_explain_analyze_compile_split(self):
        """EXPLAIN ANALYZE shows compile vs execute per operator plus
        the top-5 hot-operator footer (acceptance pin)."""
        from presto_tpu.server.dqr import DistributedQueryRunner

        with DistributedQueryRunner.tpch(scale=0.002,
                                         n_workers=2) as dqr:
            rows = dqr.execute("explain analyze " + GROUP_SQL).rows
        text = "\n".join(r[0] for r in rows)
        assert "compile ms" in text
        assert "hot operators (top" in text
        assert "by exclusive wall" in text
        assert re.search(r"\d+\.\d+ compile / \d+\.\d+ execute", text)
        assert "ms compile" in text

    def test_local_explain_analyze_compile_split(self):
        from presto_tpu.localrunner import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.002)
        res = runner.execute("explain analyze " + GROUP_SQL)
        text = "\n".join(r[0] for r in res.rows)
        assert "compile ms" in text
        assert "hot operators (top" in text
        # jit_counters grew the compile_ns attribution
        jc = runner._last_task.jit_counters()
        assert "compile_ns" in jc
        if jc["compiles"] > 0:
            assert jc["compile_ns"] > 0

    def test_kernelcache_records_compile_durations(self):
        """Fresh cache keys force a compile; the named-cache registry
        accumulates per-compile durations (record_compile)."""
        from presto_tpu.kernelcache import cache_stats
        from presto_tpu.localrunner import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.001)
        runner.execute("select l_orderkey + 4242424242 from lineitem "
                       "where l_partkey > 777777 limit 3")
        stats = cache_stats()
        compiled = [s for s in stats.values() if s["compiles"] > 0]
        assert compiled, "no cache recorded a compile"
        assert any(s["compile_ns"] > 0 for s in compiled)


class TestSlowQueryLog:
    def test_slow_query_event_and_log_line(self, caplog):
        """A query past slow_query_log_threshold_s emits ONE structured
        log line + a SlowQueryEvent with the trace token, the
        queued/execution split, and the top hot operator."""
        from presto_tpu.events import EventListener
        from presto_tpu.server.dqr import DistributedQueryRunner

        class Recorder(EventListener):
            events = []

            def slow_query(self, e):
                self.events.append(e)

        cfg = _cfg(slow_query_log_threshold_s=0.005)
        with DistributedQueryRunner.tpch(scale=0.002, n_workers=2,
                                         config=cfg) as dqr:
            dqr.event_bus.register(Recorder())
            with caplog.at_level(logging.WARNING,
                                 logger="presto_tpu.coordinator"):
                dqr.execute(GROUP_SQL)
                deadline = time.monotonic() + 5.0
                while not Recorder.events \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
        assert Recorder.events
        e = Recorder.events[-1]
        assert e.trace_token.startswith("tt-")
        assert e.elapsed_s >= e.threshold_s == 0.005
        assert e.execution_s > 0 and e.queued_s >= 0
        assert e.top_operator   # hottest operator named
        lines = [r for r in caplog.records
                 if "slow query" in r.getMessage()]
        assert lines
        msg = lines[-1].getMessage()
        assert e.trace_token in msg and "top_operator=" in msg

    def test_threshold_zero_disables(self):
        from presto_tpu.events import EventListener
        from presto_tpu.server.dqr import DistributedQueryRunner

        class Recorder(EventListener):
            events = []

            def slow_query(self, e):
                self.events.append(e)

        cfg = _cfg(slow_query_log_threshold_s=0.0)
        with DistributedQueryRunner.tpch(scale=0.002, n_workers=2,
                                         config=cfg) as dqr:
            dqr.event_bus.register(Recorder())
            dqr.execute("select count(*) from nation")
            time.sleep(0.2)
        assert Recorder.events == []


def _scrape(uri: str) -> str:
    with urllib.request.urlopen(uri, timeout=10) as resp:
        assert resp.status == 200
        return resp.read().decode()


def _parse_metrics(text: str):
    """{metric name: {frozenset(label keys)}}, {sample line: value}."""
    label_keys = {}
    values = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{([^}]*)\})?\s+(\S+)$", line)
        assert m, f"unparseable metrics line: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        keys = frozenset(kv.split("=", 1)[0]
                         for kv in labels.split(",") if kv)
        label_keys.setdefault(name, set()).add(keys)
        values[f"{name}{{{labels}}}"] = float(value)
    return label_keys, values


class TestMetricsHistograms:
    def test_latency_histograms_fed_from_dispatcher(self):
        """presto_query_{queued,execution}_seconds histograms: fixed
        buckets, cumulative counts, fed once per dispatched query —
        the scrape-side cross-check for qps_run latencies."""
        from presto_tpu.server.dqr import DistributedQueryRunner

        with DistributedQueryRunner.tpch(scale=0.002,
                                         n_workers=2) as dqr:
            dqr.execute("select count(*) from nation")
            dqr.execute("select count(*) from region")
            text = _scrape(f"{dqr.coordinator.uri}/metrics")
        for fam in ("presto_query_execution_seconds",
                    "presto_query_queued_seconds"):
            assert f"# TYPE {fam} histogram" in text
            counts = re.findall(
                rf'{fam}_bucket{{le="([^"]+)"}} (\d+)', text)
            assert counts and counts[-1][0] == "+Inf"
            # cumulative and capped by _count
            vals = [int(n) for _, n in counts]
            assert vals == sorted(vals)
            count = int(re.search(rf"{fam}_count (\d+)",
                                  text).group(1))
            assert vals[-1] == count
            assert count >= 2
        # executions take real time, queueing was ~instant: sums differ
        ex_sum = float(re.search(
            r"presto_query_execution_seconds_sum (\S+)", text).group(1))
        assert ex_sum > 0

    @pytest.mark.slow
    def test_concurrent_scrape_storm(self):
        """Satellite: a 3-client statement storm while scraping BOTH
        /metrics planes — counters monotonic across scrapes, label
        sets stable, and the scrape never 500s mid-query."""
        from presto_tpu.server.dqr import DistributedQueryRunner

        statements = [
            "select count(*) from lineitem",
            GROUP_SQL,
            "select o_orderpriority, count(*) from orders "
            "group by o_orderpriority",
        ]
        with DistributedQueryRunner.tpch(scale=0.005,
                                         n_workers=2) as dqr:
            results = {}

            def client_loop(i):
                c = dqr.new_client(user=f"storm-{i}")
                try:
                    for _ in range(3):
                        c.execute(statements[i % len(statements)])
                    results[i] = "ok"
                except Exception as e:  # noqa: BLE001
                    results[i] = e

            threads = [threading.Thread(target=client_loop, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            targets = [f"{dqr.coordinator.uri}/metrics"] + \
                [f"{w.uri}/metrics" for w in dqr.workers]
            scrapes = {t: [] for t in targets}
            while any(t.is_alive() for t in threads):
                for target in targets:
                    scrapes[target].append(_scrape(target))
                time.sleep(0.1)
            for t in threads:
                t.join()
            for target in targets:
                scrapes[target].append(_scrape(target))
            assert all(v == "ok" for v in results.values()), results
            monotonic_counters = (
                "presto_query_execution_seconds_count{}",
                "presto_query_queued_seconds_count{}",
                "presto_worker_output_pages_total{}",
                "presto_plan_cache_misses_total{}",
            )
            for target, texts in scrapes.items():
                assert len(texts) >= 2
                prev_keys, prev_vals = {}, {}
                for text in texts:
                    label_keys, values = _parse_metrics(text)
                    for name, keysets in prev_keys.items():
                        cur = label_keys.get(name)
                        if cur is None:
                            continue
                        # label KEY sets stay stable per family: every
                        # sample of one family uses one key set, and it
                        # never mutates across scrapes
                        assert keysets == cur, \
                            f"{target}: {name} label keys changed " \
                            f"{keysets} -> {cur}"
                    # counters the storm drives are monotonic
                    for c in monotonic_counters:
                        if c in values and c in prev_vals:
                            assert values[c] >= prev_vals[c], \
                                f"{target}: {c} regressed"
                    prev_keys = {n: set(k) for n, k
                                 in label_keys.items()}
                    prev_vals = values
