"""Scalar function library, SQL-level, vs Python-computed expectations.

The reference's analogue coverage: operator/scalar Test* classes
(presto-main/src/test/.../operator/scalar/, e.g. TestMathFunctions,
TestStringFunctions, TestDateTimeFunctions)."""

import datetime
import math

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestMath:
    def test_trig(self, runner):
        row = one(runner, "select sin(1.0), cos(1.0), tan(1.0), "
                          "asin(0.5), acos(0.5), atan(1.0), atan2(1.0, 2.0)")
        want = (math.sin(1), math.cos(1), math.tan(1), math.asin(.5),
                math.acos(.5), math.atan(1), math.atan2(1, 2))
        for got, exp in zip(row, want):
            assert math.isclose(got, exp)

    def test_hyperbolic_logs(self, runner):
        row = one(runner, "select sinh(1.0), cosh(1.0), tanh(1.0), "
                          "log2(8.0), log10(1000.0), ln(e()), exp(1.0)")
        want = (math.sinh(1), math.cosh(1), math.tanh(1), 3.0, 3.0, 1.0,
                math.e)
        for got, exp in zip(row, want):
            assert math.isclose(got, exp)

    def test_rounding_family(self, runner):
        row = one(runner, "select truncate(2.9), truncate(-2.9), "
                          "round(2.5), round(-2.5), round(2.345, 2), "
                          "ceil(2.1), floor(-2.1), cbrt(8.0)")
        assert row[:7] == (2.0, -2.0, 3.0, -3.0, 2.35, 3.0, -3.0)
        assert math.isclose(row[7], 2.0)

    def test_misc(self, runner):
        row = one(runner, "select abs(-7), sign(-3.5), mod(7, 3), "
                          "mod(-7, 3), power(2.0, 10.0), sqrt(2.0)")
        assert row[:4] == (7, -1.0, 1, -1)
        assert row[4] == 1024.0
        assert math.isclose(row[5], math.sqrt(2))

    def test_greatest_least_mixed(self, runner):
        row = one(runner, "select greatest(1, 2.5, 2), least(1, 2.5, 0), "
                          "greatest(3, 1), least(-1, -5)")
        assert row == (2.5, 0.0, 3, -5)

    def test_bitwise(self, runner):
        row = one(runner, "select bitwise_and(12, 10), bitwise_or(12, 10), "
                          "bitwise_xor(12, 10), bitwise_not(5)")
        assert row == (8, 14, 6, -6)

    def test_float_predicates(self, runner):
        row = one(runner, "select is_nan(nan()), is_finite(1.0), "
                          "is_infinite(infinity()), is_nan(1.0)")
        assert row == (True, True, True, False)


class TestString:
    def test_pad_split(self, runner):
        row = one(runner, "select lpad('ab', 5, 'xy'), rpad('ab', 5, 'xy'),"
                          " lpad('abcdef', 3, 'x'), "
                          "split_part('a:b:c', ':', 2)")
        assert row == ("xyxab", "abxyx", "abc", "b")

    def test_split_part_null(self, runner):
        row = one(runner, "select split_part('a:b', ':', 9) is null")
        assert row == (True,)

    def test_chr_codepoint(self, runner):
        row = one(runner, "select chr(9731), codepoint('A')")
        assert row == ("☃", 65)

    def test_translate_distance(self, runner):
        row = one(runner,
                  "select translate('abcd', 'abc', '12'), "
                  "levenshtein_distance('kitten', 'sitting'), "
                  "hamming_distance('karolin', 'kathrin')")
        assert row == ("12d", 3, 3)

    def test_regex(self, runner):
        row = one(runner,
                  "select regexp_like('plane', 'an'), "
                  "regexp_extract('1a 2b 3c', '(\\d+)([a-z])', 2), "
                  "regexp_replace('1a 2b', '\\d', '#'), "
                  "regexp_extract('xyz', '\\d+') is null")
        assert row == (True, "a", "#a #b", True)

    def test_classic_string_fns_on_column(self, runner):
        rows = runner.execute(
            "select upper(n_name), length(n_name), reverse(n_name), "
            "strpos(n_name, 'A'), ends_with(n_name, 'A') "
            "from nation where n_name = 'ALGERIA'").rows
        assert rows == [("ALGERIA", 7, "AIREGLA", 1, True)]


class TestDatetime:
    def test_date_trunc(self, runner):
        row = one(runner, "select date_trunc('year', date '1995-07-17'), "
                          "date_trunc('quarter', date '1995-07-17'), "
                          "date_trunc('month', date '1995-07-17'), "
                          "date_trunc('week', date '1995-07-17')")
        d = datetime.date
        assert row == (d(1995, 1, 1), d(1995, 7, 1), d(1995, 7, 1),
                       d(1995, 7, 17))  # 1995-07-17 is a Monday

    def test_date_trunc_timestamp(self, runner):
        row = one(runner,
                  "select date_trunc('hour', "
                  "timestamp '1995-07-17 13:45:31'), "
                  "date_trunc('day', timestamp '1995-07-17 13:45:31')")
        dt = datetime.datetime
        assert row == (dt(1995, 7, 17, 13), dt(1995, 7, 17))

    def test_date_diff_add(self, runner):
        row = one(runner,
                  "select date_diff('day', date '1995-01-01', "
                  "date '1995-03-01'), "
                  "date_diff('week', date '1995-01-01', date '1995-01-20'),"
                  "date_diff('month', date '1995-01-31', "
                  "date '1995-03-01'), "
                  "date_add('day', 30, date '1995-01-15'), "
                  "date_add('year', -1, date '1996-02-29')")
        d = datetime.date
        assert row == (59, 2, 2, d(1995, 2, 14), d(1995, 2, 28))

    def test_extract_time_fields(self, runner):
        row = one(runner,
                  "select extract(hour from "
                  "timestamp '1995-07-17 13:45:31'), "
                  "extract(minute from timestamp '1995-07-17 13:45:31'), "
                  "extract(second from timestamp '1995-07-17 13:45:31'), "
                  "extract(year from date '1995-07-17'), "
                  "extract(quarter from date '1995-07-17'), "
                  "extract(day from date '1995-07-17')")
        assert row == (13, 45, 31, 1995, 3, 17)

    def test_unixtime(self, runner):
        row = one(runner,
                  "select to_unixtime(timestamp '1970-01-02 00:00:00'), "
                  "from_unixtime(86400.0)")
        assert row[0] == 86400.0
        assert row[1] == datetime.datetime(1970, 1, 2)

    def test_last_day_of_month(self, runner):
        row = one(runner, "select last_day_of_month(date '1996-02-10'), "
                          "last_day_of_month(date '1995-12-05')")
        assert row == (datetime.date(1996, 2, 29),
                       datetime.date(1995, 12, 31))


class TestConditional:
    def test_if(self, runner):
        row = one(runner, "select if(true, 1, 2), if(false, 1, 2), "
                          "if(1 > 2, 'y'), if(2 > 1, 'y') ")
        assert row == (1, 2, None, "y")

    def test_nullif_coalesce(self, runner):
        row = one(runner, "select nullif(5, 5), nullif(5, 3), "
                          "coalesce(null, null, 7), coalesce(1, 2)")
        assert row == (None, 5, 7, 1)


class TestAggregateExtras:
    def test_bool_aggs(self, runner):
        rows = runner.execute(
            "select bool_and(n_regionkey < 5), bool_or(n_regionkey > 3), "
            "every(n_regionkey >= 0) from nation").rows
        assert rows == [(True, True, True)]

    def test_any_value(self, runner):
        rows = runner.execute(
            "select any_value(n_name) from nation "
            "where n_name = 'KENYA'").rows
        assert rows == [("KENYA",)]
