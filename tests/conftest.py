"""Test bootstrap: force an 8-device virtual CPU mesh.

Multi-chip behavior is tested the way the reference tests multi-node
behavior — in one process (DistributedQueryRunner boots coordinator+workers
in one JVM, presto-testing/.../DistributedQueryRunner.java:73).  Here the
"cluster" is 8 virtual XLA CPU devices, so sharding/collective code paths
compile and execute without TPU hardware.

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
