"""Test bootstrap: force an 8-device virtual CPU mesh.

Multi-chip behavior is tested the way the reference tests multi-node
behavior — in one process (DistributedQueryRunner boots coordinator+workers
in one JVM, presto-testing/.../DistributedQueryRunner.java:73).  Here the
"cluster" is 8 virtual XLA CPU devices, so sharding/collective code paths
compile and execute without TPU hardware.

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

# Force the CPU backend even when the environment points JAX at real TPU
# hardware (JAX_PLATFORMS=axon + a sitecustomize hook that re-selects the
# axon platform): unit tests must be hardware-independent and fast; the
# driver benchmarks on real chips separately.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
def _flag_supported(flag: str) -> bool:
    """XLA hard-aborts the process on unknown XLA_FLAGS entries
    (parse_flags_from_env.cc "Unknown flags"), so an optional flag the
    installed jaxlib predates/dropped must be probed in a throwaway
    subprocess before it poisons every backend init in the suite."""
    import subprocess
    import sys

    env = dict(os.environ, XLA_FLAGS=flag, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, capture_output=True, timeout=300)
    except Exception:  # noqa: BLE001 - treat probe failure as unsupported
        return False
    return proc.returncode == 0


_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
_collective = "--xla_cpu_collective_call_terminate_timeout_seconds=1200"
if "collective_call_terminate_timeout" not in _flags \
        and _flag_supported(_collective):
    # single-core hosts run the 8 virtual devices' shards sequentially;
    # XLA's default 40s collective-rendezvous abort is too eager for
    # the larger mesh-SQL programs (the wait is progress, not deadlock)
    _flags = (_flags + " " + _collective)
os.environ["XLA_FLAGS"] = _flags

# The sitecustomize hook may already have switched jax_platforms to the
# axon TPU plugin; switch back before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
