"""Device-kernel tests, diffed against naive Python oracles
(reference tier: TestGroupByHash / TestHashJoinOperator golden-page style,
SURVEY §4.1)."""

import collections

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from presto_tpu import types as T  # noqa: E402
from presto_tpu.ops import join as J  # noqa: E402
from presto_tpu.ops.filter import selected_positions  # noqa: E402
from presto_tpu.ops.groupby import global_aggregate, grouped_aggregate  # noqa: E402
from presto_tpu.ops.hashing import partition_of, row_hash  # noqa: E402
from presto_tpu.ops.sort import sort_permutation  # noqa: E402


def pad_to(a, cap, fill=0):
    a = np.asarray(a)
    out = np.full(cap, fill, a.dtype)
    out[: len(a)] = a
    return out


# ---------------------------------------------------------------------------
# grouped aggregation
# ---------------------------------------------------------------------------

def test_grouped_aggregate_single_key():
    rng = np.random.default_rng(0)
    n, cap, gcap = 1000, 1024, 64
    keys = rng.integers(0, 37, n).astype(np.int64)
    vals = rng.integers(-100, 100, n).astype(np.int64)
    gi, ng, results = grouped_aggregate(
        [(jnp.asarray(pad_to(keys, cap)), None, T.BIGINT)],
        [("sum", jnp.asarray(pad_to(vals, cap)), None),
         ("count", jnp.asarray(pad_to(vals, cap)), None),
         ("min", jnp.asarray(pad_to(vals, cap)), None),
         ("max", jnp.asarray(pad_to(vals, cap)), None)],
        jnp.asarray(n), gcap)
    ng = int(ng)
    expected = {}
    for k, v in zip(keys, vals):
        e = expected.setdefault(k, [0, 0, 10**9, -10**9])
        e[0] += v
        e[1] += 1
        e[2] = min(e[2], v)
        e[3] = max(e[3], v)
    assert ng == len(expected)
    out_keys = np.asarray(jnp.asarray(pad_to(keys, cap))[gi])[:ng]
    sums = np.asarray(results[0][0])[:ng]
    cnts = np.asarray(results[1][0])[:ng]
    mins = np.asarray(results[2][0])[:ng]
    maxs = np.asarray(results[3][0])[:ng]
    assert sorted(out_keys) == sorted(expected)
    for k, s, c, lo, hi in zip(out_keys, sums, cnts, mins, maxs):
        e = expected[k]
        assert (s, c, lo, hi) == (e[0], e[1], e[2], e[3])


def test_grouped_aggregate_multi_key_with_nulls():
    # keys: (a, b) where b has nulls; SQL groups nulls together
    a = np.array([1, 1, 2, 2, 1, 1], dtype=np.int64)
    b = np.array([10, 10, 20, 20, 0, 0], dtype=np.int64)
    bvalid = np.array([True, True, True, True, False, False])
    v = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    cap, gcap = 8, 8
    gi, ng, results = grouped_aggregate(
        [(jnp.asarray(pad_to(a, cap)), None, T.BIGINT),
         (jnp.asarray(pad_to(b, cap)), jnp.asarray(pad_to(bvalid, cap)),
          T.BIGINT)],
        [("sum", jnp.asarray(pad_to(v, cap)), None)],
        jnp.asarray(6), gcap)
    assert int(ng) == 3
    sums = sorted(np.asarray(results[0][0])[:3].tolist())
    assert sums == [3.0, 7.0, 11.0]


def test_grouped_aggregate_null_values_and_overflow():
    # agg input nulls are ignored; count counts non-null only
    k = np.array([1, 1, 2], dtype=np.int64)
    v = np.array([5.0, 0.0, 7.0])
    vvalid = np.array([True, False, True])
    gi, ng, results = grouped_aggregate(
        [(jnp.asarray(pad_to(k, 4)), None, T.BIGINT)],
        [("sum", jnp.asarray(pad_to(v, 4)), jnp.asarray(pad_to(vvalid, 4))),
         ("count", jnp.asarray(pad_to(v, 4)), jnp.asarray(pad_to(vvalid, 4)))],
        jnp.asarray(3), 8)
    assert int(ng) == 2
    cnt = np.asarray(results[1][0])[:2]
    assert sorted(cnt.tolist()) == [1, 1]
    # overflow: 5 distinct keys, capacity 4 -> num_groups reports 5
    k5 = np.arange(5, dtype=np.int64)
    gi, ng, _ = grouped_aggregate(
        [(jnp.asarray(pad_to(k5, 8)), None, T.BIGINT)],
        [("count", jnp.asarray(pad_to(k5, 8)), None)],
        jnp.asarray(5), 4)
    assert int(ng) == 5  # caller re-runs with bigger capacity


def test_grouped_aggregate_empty():
    gi, ng, results = grouped_aggregate(
        [(jnp.zeros(8, jnp.int64), None, T.BIGINT)],
        [("sum", jnp.zeros(8, jnp.float64), None)],
        jnp.asarray(0), 4)
    assert int(ng) == 0


def test_global_aggregate():
    v = np.array([1.0, 2.0, 3.0, 0.0])
    valid = np.array([True, True, False, True])
    results = global_aggregate(
        [("sum", jnp.asarray(v), jnp.asarray(valid)),
         ("count", jnp.asarray(v), jnp.asarray(valid)),
         ("min", jnp.asarray(v), jnp.asarray(valid)),
         ("max", jnp.asarray(v), jnp.asarray(valid))],
        jnp.asarray(4))
    assert float(results[0][0]) == 3.0  # 1 + 2 + 0 (3.0 is NULL)
    assert int(results[1][0]) == 3
    assert float(results[2][0]) == 0.0
    assert float(results[3][0]) == 2.0


def test_global_aggregate_empty_input():
    results = global_aggregate(
        [("sum", jnp.zeros(4), None)], jnp.asarray(0))
    assert int(results[0][1]) == 0  # count 0 -> SQL NULL sum


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

def reference_inner_join(bkeys, pkeys):
    build_pos = collections.defaultdict(list)
    for i, k in enumerate(bkeys):
        build_pos[k].append(i)
    out = []
    for j, k in enumerate(pkeys):
        for i in build_pos.get(k, []):
            out.append((j, i))
    return out


def run_join(bkeys, pkeys, cap_b=None, cap_p=None, out_cap=64):
    cap_b = cap_b or len(bkeys)
    cap_p = cap_p or len(pkeys)
    bids, pids = J.single_word_ids(
        (jnp.asarray(pad_to(bkeys, cap_b)), None, T.BIGINT),
        (jnp.asarray(pad_to(pkeys, cap_p)), None, T.BIGINT),
        jnp.asarray(len(bkeys)), jnp.asarray(len(pkeys)))
    sb, perm_b = J.build_index(bids)
    lo, counts = J.probe_counts(sb, perm_b, pids)
    return bids, pids, sb, perm_b, lo, counts


def test_inner_join_with_duplicates():
    bkeys = [1, 2, 2, 3, 5]
    pkeys = [2, 3, 4, 2, 1]
    bids, pids, sb, perm_b, lo, counts = run_join(bkeys, pkeys)
    probe_idx, build_idx, valid, _, total = J.expand_matches(
        lo, counts, perm_b, 16)
    got = sorted((int(p), int(b)) for p, b, ok in
                 zip(probe_idx, build_idx, valid) if ok)
    assert got == sorted(reference_inner_join(bkeys, pkeys))
    assert int(total) == len(got)


def test_left_outer_join():
    bkeys = [1, 2, 2]
    pkeys = [2, 4, 1]
    bids, pids, sb, perm_b, lo, counts = run_join(bkeys, pkeys)
    live = pids >= 0
    probe_idx, build_idx, valid, unmatched, total = J.expand_matches_outer(
        lo, counts, live, perm_b, 16)
    rows = [(int(p), int(b), bool(u)) for p, b, u, ok in
            zip(probe_idx, build_idx, unmatched, valid) if ok]
    assert int(total) == 4
    # probe row 1 (key 4) must appear exactly once, unmatched
    assert (1, 0, True) in rows
    matched = [(p, b) for p, b, u in rows if not u]
    assert sorted(matched) == [(0, 1), (0, 2), (2, 0)]


def test_semi_anti():
    bkeys = [2, 3]
    pkeys = [1, 2, 3, 4]
    bids, pids, sb, perm_b, lo, counts = run_join(bkeys, pkeys)
    live = pids >= 0
    semi = np.asarray(J.semi_mask(counts, live, anti=False))
    anti = np.asarray(J.semi_mask(counts, live, anti=True))
    assert semi.tolist() == [False, True, True, False]
    assert anti.tolist() == [True, False, False, True]


def test_null_keys_never_match():
    cap = 4
    bvals = jnp.asarray(pad_to([1, 2], cap))
    bvalid = jnp.asarray(pad_to([True, False], cap))
    pvals = jnp.asarray(pad_to([1, 2], cap))
    pvalid = jnp.asarray(pad_to([False, True], cap))
    bids, pids = J.single_word_ids(
        (bvals, bvalid, T.BIGINT), (pvals, pvalid, T.BIGINT),
        jnp.asarray(2), jnp.asarray(2))
    sb, perm_b = J.build_index(bids)
    lo, counts = J.probe_counts(sb, perm_b, pids)
    assert np.asarray(counts).tolist() == [0, 0, 0, 0]


def test_multi_key_canonical_ids():
    bk = [(1, 10), (1, 20), (2, 10)]
    pk = [(1, 10), (2, 10), (2, 20), (1, 20)]
    cap = 4
    build_cols = [
        (jnp.asarray(pad_to([a for a, _ in bk], cap)), None, T.BIGINT),
        (jnp.asarray(pad_to([b for _, b in bk], cap)), None, T.BIGINT)]
    probe_cols = [
        (jnp.asarray(pad_to([a for a, _ in pk], cap)), None, T.BIGINT),
        (jnp.asarray(pad_to([b for _, b in pk], cap)), None, T.BIGINT)]
    bids, pids = J.canonical_ids(build_cols, probe_cols,
                                 jnp.asarray(3), jnp.asarray(4))
    sb, perm_b = J.build_index(bids)
    lo, counts = J.probe_counts(sb, perm_b, pids)
    probe_idx, build_idx, valid, _, total = J.expand_matches(
        lo, counts, perm_b, 16)
    got = sorted((int(p), int(b)) for p, b, ok in
                 zip(probe_idx, build_idx, valid) if ok)
    assert got == sorted(reference_inner_join(bk, pk))


def test_search_path_probe_key_equals_build_max():
    """Wide key span forces the binary-search fallback; probe keys equal
    to the build-side max must emit exactly one row each (regression:
    _lower_bound without the lo<hi guard overshot to n+1 and
    duplicated every max-key match)."""
    span = 40_000  # > dense scratch minimum (1 << 14)
    bkeys = [0, 7, 7, span]
    pkeys = [span, span, 7, -3]
    bids, pids, sb, perm_b, lo, counts = run_join(bkeys, pkeys)
    assert np.asarray(counts).tolist() == [1, 1, 2, 0]
    probe_idx, build_idx, valid, _, total = J.expand_matches(
        lo, counts, perm_b, 16)
    got = sorted((int(p), int(b)) for p, b, ok in
                 zip(probe_idx, build_idx, valid) if ok)
    assert got == sorted(reference_inner_join(bkeys, pkeys))


def test_matched_build_mask():
    bkeys = [1, 2, 2, 9]
    pkeys = [2, 7]
    bids, pids, sb, perm_b, lo, counts = run_join(bkeys, pkeys)
    matched = np.asarray(J.matched_build_mask(lo, counts, 4, perm_b))
    assert matched.tolist() == [False, True, True, False]


def test_join_overflow_reports_total():
    bkeys = [1] * 10
    pkeys = [1] * 10
    bids, pids, sb, perm_b, lo, counts = run_join(bkeys, pkeys)
    _, _, valid, _, total = J.expand_matches(lo, counts, perm_b, 16)
    assert int(total) == 100  # exceeds out_cap; host re-runs bigger
    assert int(np.asarray(valid).sum()) == 16


# ---------------------------------------------------------------------------
# filter / sort / hash
# ---------------------------------------------------------------------------

def test_selected_positions_exact():
    mask = jnp.asarray([True, False, True, True, False, True, False, False])
    idx, cnt = selected_positions(mask, None, jnp.asarray(6), 8)
    assert int(cnt) == 4
    assert np.asarray(idx)[:4].tolist() == [0, 2, 3, 5]
    valid = jnp.asarray([True, True, False, True, True, True, True, True])
    idx, cnt = selected_positions(mask, valid, jnp.asarray(6), 8)
    assert int(cnt) == 3
    assert np.asarray(idx)[:3].tolist() == [0, 3, 5]


def test_sort_permutation():
    vals = np.array([3.0, 1.0, 2.0, 0.0, 9.9], dtype=np.float64)
    valid = np.array([True, True, True, False, True])
    perm = sort_permutation(
        [(jnp.asarray(vals), jnp.asarray(valid), T.DOUBLE, False, False)],
        jnp.asarray(5))
    # ascending, nulls last: 1.0, 2.0, 3.0, 9.9, NULL
    assert np.asarray(perm).tolist() == [1, 2, 0, 4, 3]
    perm = sort_permutation(
        [(jnp.asarray(vals), jnp.asarray(valid), T.DOUBLE, True, True)],
        jnp.asarray(5))
    # descending, nulls first
    assert np.asarray(perm).tolist() == [3, 4, 0, 2, 1]


def test_sort_negative_floats_and_padding():
    vals = np.array([-1.5, 2.0, -3.0, 0.0, 99.0, 99.0], dtype=np.float64)
    perm = sort_permutation(
        [(jnp.asarray(vals), None, T.DOUBLE, False, False)],
        jnp.asarray(4))  # rows 4,5 are padding
    assert np.asarray(perm)[:4].tolist() == [2, 0, 3, 1]


def test_sort_multi_key():
    a = np.array([1, 2, 1, 2], dtype=np.int64)
    b = np.array([9, 8, 7, 6], dtype=np.int64)
    perm = sort_permutation(
        [(jnp.asarray(a), None, T.BIGINT, False, False),
         (jnp.asarray(b), None, T.BIGINT, True, False)],
        jnp.asarray(4))
    # a asc, b desc: (1,9),(1,7),(2,8),(2,6)
    assert np.asarray(perm).tolist() == [0, 2, 1, 3]


def test_row_hash_partitions():
    vals = jnp.asarray(np.arange(1000, dtype=np.int64))
    h = row_hash([(vals, None, T.BIGINT)])
    parts = np.asarray(partition_of(h, 8))
    # roughly balanced
    counts = np.bincount(parts, minlength=8)
    assert counts.min() > 80
    # deterministic
    h2 = row_hash([(vals, None, T.BIGINT)])
    assert np.array_equal(np.asarray(h), np.asarray(h2))


class TestDirectGroupby:
    """direct (mixed-radix + segment reduce) vs sort-based grouped
    aggregation must agree, including nullable keys and fused filters."""

    def _run_both(self, key_codes_np, key_valid_np, doms, vals_np,
                  vals_valid_np, live_np, n):
        import jax.numpy as jnp

        from presto_tpu import types as T
        from presto_tpu.ops.groupby import (
            decode_direct_keys, direct_grouped_aggregate, grouped_aggregate,
        )

        keys = [(jnp.asarray(c), None if v is None else jnp.asarray(v))
                for c, v in zip(key_codes_np, key_valid_np)]
        aggs = [("sum", jnp.asarray(vals_np),
                 None if vals_valid_np is None else jnp.asarray(vals_valid_np)),
                ("count", jnp.asarray(vals_np), None),
                ("min", jnp.asarray(vals_np), None),
                ("max", jnp.asarray(vals_np), None)]
        live = None if live_np is None else jnp.asarray(live_np)
        present, results = direct_grouped_aggregate(
            keys, doms, aggs, jnp.asarray(n), live_mask=live)
        slots = jnp.nonzero(present, size=present.shape[0], fill_value=0)[0]
        ngd = int(present.sum())
        decoded = decode_direct_keys(
            slots, [v is not None for v in key_valid_np], doms)
        direct = {}
        for i in range(ngd):
            key = tuple(
                None if (valid is not None and not bool(valid[i]))
                else int(codes[i]) for codes, valid in decoded)
            direct[key] = tuple(
                int(np.asarray(v)[slots[i]]) for v, _ in results)

        # sort path needs compacted live rows; emulate by masking via numpy
        mask = np.ones(len(vals_np), bool) if live_np is None else live_np.copy()
        mask &= np.arange(len(vals_np)) < n
        idx = np.nonzero(mask)[0]
        cap = max(1, 1 << int(np.ceil(np.log2(max(len(idx), 1)))))
        def padc(a, fill=0):
            out = np.full(cap, fill, dtype=np.asarray(a).dtype)
            out[:len(idx)] = np.asarray(a)[idx]
            return jnp.asarray(out)
        skeys = []
        for c, v in zip(key_codes_np, key_valid_np):
            skeys.append((padc(c), None if v is None else padc(v, False),
                          T.INTEGER))
        saggs = [("sum", padc(vals_np),
                  None if vals_valid_np is None else padc(vals_valid_np, False)),
                 ("count", padc(vals_np), None),
                 ("min", padc(vals_np), None),
                 ("max", padc(vals_np), None)]
        gi, ng, sres = grouped_aggregate(skeys, saggs, jnp.asarray(len(idx)),
                                         cap)
        ngs = int(ng)
        sorted_out = {}
        for i in range(ngs):
            row = int(np.asarray(gi)[i])
            key = []
            for c, v in zip(key_codes_np, key_valid_np):
                cc = padc(c); vv = None if v is None else padc(v, False)
                key.append(None if (vv is not None and not bool(np.asarray(vv)[row]))
                           else int(np.asarray(cc)[row]))
            sorted_out[tuple(key)] = tuple(
                int(np.asarray(v)[i]) for v, _ in sres)
        return direct, sorted_out

    def test_matches_sort_path_with_nulls_and_filter(self):
        rng = np.random.default_rng(5)
        n, cap = 900, 1024
        k1 = rng.integers(0, 5, cap).astype(np.int32)
        k1v = rng.random(cap) > 0.2
        k2 = rng.integers(0, 3, cap).astype(np.int32)
        vals = rng.integers(-100, 100, cap)
        vv = rng.random(cap) > 0.1
        live = rng.random(cap) > 0.3
        direct, sorted_out = self._run_both(
            [k1, k2], [k1v, None], [5, 3], vals, vv, live, n)
        assert direct == sorted_out
        assert len(direct) > 0

    def test_null_key_forms_one_group(self):
        k = np.zeros(8, np.int32)
        kv = np.array([True, False, True, False] * 2)
        vals = np.arange(8)
        direct, sorted_out = self._run_both(
            [k], [kv], [1], vals, None, None, 8)
        assert direct == sorted_out
        assert set(direct) == {(0,), (None,)}
