"""Lakehouse (hive-role) connector tests: formats, partitioned layout,
partition pruning, SQL end-to-end (reference: presto-hive HiveMetadata/
HivePartitionManager/HiveSplitManager + presto-orc/parquet format libs)."""

import os

import pytest

from presto_tpu.connectors.lakehouse import LakehouseConnector
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture()
def runner(tmp_path):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("lake", LakehouseConnector(str(tmp_path)))
    return r


FORMATS = ["csv", "json", "parquet", "orc"]


@pytest.mark.parametrize("fmt", FORMATS)
def test_roundtrip_formats(runner, fmt):
    runner.execute(
        f"CREATE TABLE lake.t_{fmt} (a bigint, b varchar, c double, "
        f"d date, e boolean) WITH (format = '{fmt}')")
    runner.execute(
        f"INSERT INTO lake.t_{fmt} VALUES "
        "(1, 'x', 1.5, DATE '2020-01-02', true), "
        "(2, NULL, -0.25, DATE '1999-12-31', false), "
        "(3, 'z z', 0.0, NULL, NULL)")
    got = sorted(runner.execute(f"SELECT * FROM lake.t_{fmt}").rows)
    import datetime

    assert got[0] == (1, "x", 1.5, datetime.date(2020, 1, 2), True)
    assert got[1][1] is None and got[1][2] == -0.25
    assert got[2][3] is None and got[2][4] is None
    # column pruning + filter
    assert runner.execute(
        f"SELECT b FROM lake.t_{fmt} WHERE a = 1").rows == [("x",)]


def test_ctas_from_tpch(runner):
    runner.execute("CREATE TABLE lake.nation_copy WITH (format = 'json') "
                   "AS SELECT n_nationkey, n_name, n_regionkey "
                   "FROM tpch.nation")
    assert runner.execute(
        "SELECT count(*) FROM lake.nation_copy").rows == [(25,)]
    a = sorted(runner.execute(
        "SELECT n_name FROM lake.nation_copy WHERE n_regionkey = 2").rows)
    b = sorted(runner.execute(
        "SELECT n_name FROM tpch.nation WHERE n_regionkey = 2").rows)
    assert a == b


def test_partitioned_write_layout(runner, tmp_path):
    runner.execute(
        "CREATE TABLE lake.pt (v bigint, region bigint) "
        "WITH (format = 'csv', partitioned_by = ARRAY['region'])")
    runner.execute("INSERT INTO lake.pt VALUES (1, 10), (2, 10), (3, 20)")
    # hive directory layout: region=<value>/part-*.csv
    assert sorted(os.listdir(tmp_path / "pt")) == [
        "_schema.json", "region=10", "region=20"]
    # partition column not stored in the data files
    files = os.listdir(tmp_path / "pt" / "region=10")
    body = (tmp_path / "pt" / "region=10" / files[0]).read_text()
    assert "10" not in body
    got = sorted(runner.execute("SELECT region, v FROM lake.pt").rows)
    assert got == [(10, 1), (10, 2), (20, 3)]


def test_partition_pruning(runner):
    conn = runner.registry.get("lake")
    runner.execute(
        "CREATE TABLE lake.pp (v bigint, d date) "
        "WITH (partitioned_by = ARRAY['d'])")
    runner.execute(
        "INSERT INTO lake.pp VALUES "
        "(1, DATE '2020-01-01'), (2, DATE '2020-01-02'), "
        "(3, DATE '2020-01-03')")
    handle = conn.get_table("pp")
    splits = conn.get_splits(handle, 1)
    assert len(splits) == 3
    # prune via the connector API with storage-domain (epoch-day) literal
    import datetime

    day2 = (datetime.date(2020, 1, 2) - datetime.date(1970, 1, 1)).days
    live = conn.prune_splits(handle, splits, [("d", "ge", day2)])
    assert len(live) == 2
    # and end-to-end: the engine extracts the constraint and the query
    # still answers correctly from the pruned split set
    got = runner.execute(
        "SELECT sum(v) FROM lake.pp WHERE d >= DATE '2020-01-02'").rows
    assert got == [(5,)]
    got = runner.execute(
        "SELECT sum(v) FROM lake.pp WHERE d = DATE '2020-01-01'").rows
    assert got == [(1,)]


def test_pruning_observed(runner, monkeypatch):
    """Prove files are skipped: count page_source calls."""
    conn = runner.registry.get("lake")
    runner.execute(
        "CREATE TABLE lake.po (v bigint, k bigint) "
        "WITH (partitioned_by = ARRAY['k'])")
    for k in range(4):
        runner.execute(f"INSERT INTO lake.po VALUES ({k}, {k})")
    opened = []
    orig = LakehouseConnector.page_source

    def counting(self, split, columns, batch_rows=65536):
        opened.append(split.info[0])
        return orig(self, split, columns, batch_rows)

    monkeypatch.setattr(LakehouseConnector, "page_source", counting)
    got = runner.execute(
        "SELECT sum(v) FROM lake.po WHERE k IN (1, 3)").rows
    assert got == [(4,)]
    assert len(opened) == 2  # two of four partitions opened


def test_analyze_stats_rename_drop(runner):
    runner.execute("CREATE TABLE lake.s (a bigint, b varchar)")
    runner.execute("INSERT INTO lake.s VALUES (1,'x'),(2,NULL),(3,'y')")
    runner.execute("ANALYZE lake.s")
    stats = runner.execute("SHOW STATS FOR lake.s").rows
    by_col = {r[0]: r for r in stats}
    assert by_col[None][4] == 3.0
    assert by_col["b"][3] == pytest.approx(1 / 3)
    runner.execute("ALTER TABLE lake.s RENAME TO s2")
    assert runner.execute("SELECT count(*) FROM lake.s2").rows == [(3,)]
    runner.execute("DROP TABLE lake.s2")
    assert ("s2",) not in runner.execute("SHOW TABLES").rows


@pytest.mark.slow
def test_join_lake_with_tpch(runner):
    runner.execute("CREATE TABLE lake.regions WITH (format='parquet') AS "
                   "SELECT r_regionkey, r_name FROM tpch.region")
    got = runner.execute(
        "SELECT r.r_name, count(*) FROM tpch.nation n "
        "JOIN lake.regions r ON n.n_regionkey = r.r_regionkey "
        "GROUP BY r.r_name ORDER BY r.r_name").rows
    assert len(got) == 5 and all(c == 5 for _, c in got)


def test_empty_table_scan(runner):
    runner.execute("CREATE TABLE lake.e (a bigint)")
    assert runner.execute("SELECT count(*) FROM lake.e").rows == [(0,)]


def test_null_partition_values(runner):
    runner.execute(
        "CREATE TABLE lake.np (v bigint, p bigint) "
        "WITH (partitioned_by = ARRAY['p'])")
    runner.execute("INSERT INTO lake.np VALUES (1, 10), (2, NULL)")
    got = sorted(runner.execute("SELECT v, p FROM lake.np").rows)
    assert got == [(1, 10), (2, None)]
    assert runner.execute(
        "SELECT v FROM lake.np WHERE p IS NULL").rows == [(2,)]


def test_partition_value_escaping(runner):
    runner.execute(
        "CREATE TABLE lake.esc (v bigint, p varchar) "
        "WITH (partitioned_by = ARRAY['p'])")
    runner.execute("INSERT INTO lake.esc VALUES (1, 'a/b'), (2, 'c'), "
                   "(3, '__DEFAULT_PARTITION__'), (4, NULL)")
    got = sorted(runner.execute("SELECT v, p FROM lake.esc").rows)
    assert got == [(1, "a/b"), (2, "c"), (3, "__DEFAULT_PARTITION__"),
                   (4, None)]
    assert runner.execute(
        "SELECT v FROM lake.esc WHERE p = 'a/b'").rows == [(1,)]
    assert runner.execute(
        "SELECT v FROM lake.esc WHERE p = '__DEFAULT_PARTITION__'"
    ).rows == [(3,)]


def test_parquet_rowgroup_stats_pruning(tmp_path):
    """Row-group splits + min/max stats pruning (presto-parquet predicate
    pushdown role, ParquetReader.java:64): groups whose range cannot
    match the pushed conjunct never reach the scan."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from presto_tpu.connectors.lakehouse import LakehouseConnector

    conn = LakehouseConnector(str(tmp_path))
    runner = LocalQueryRunner.tpch(scale=0.01)
    runner.registry.register("lake2", conn)
    runner.execute("CREATE TABLE lake2.rg (k BIGINT, v DOUBLE) "
                   "WITH (format = 'parquet')")
    # write one file with 4 row groups of ascending k ranges
    h = conn.get_table("rg")
    tdir = conn._table_dir("rg")
    import os
    table = pa.table({"k": pa.array(range(4000), pa.int64()),
                      "v": pa.array([float(i) for i in range(4000)])})
    pq.write_table(table, os.path.join(tdir, "part-0.parquet"),
                   row_group_size=1000)
    splits = conn.get_splits(h, 8)
    assert len(splits) == 4                      # one per row group
    pruned = conn.prune_splits(h, splits, [("k", "lt", 500)])
    assert len(pruned) == 1                      # only group [0,1000)
    pruned = conn.prune_splits(h, splits, [("k", "ge", 3500)])
    assert len(pruned) == 1                      # only group [3000,4000)
    pruned = conn.prune_splits(h, splits, [("k", "in", (1500, 2500))])
    assert len(pruned) == 2
    # end-to-end: results unchanged with pruning in play
    got = runner.execute(
        "SELECT count(*), sum(v) FROM lake2.rg WHERE k < 500").rows
    assert got == [(500, float(sum(range(500))))]


def test_orc_stripe_stats_pruning(tmp_path):
    """Stripe splits + min/max stats pruning for ORC (presto-orc's
    stripe predicate pushdown role, OrcRecordReader.java:72/356), via
    our own footer/metadata parse (orcmeta.py — pyarrow exposes no
    stripe-statistics values).  Mirrors the parquet row-group test."""
    import os

    import pyarrow as pa
    import pyarrow.orc as po

    from presto_tpu.connectors.lakehouse import LakehouseConnector

    conn = LakehouseConnector(str(tmp_path))
    runner = LocalQueryRunner.tpch(scale=0.01)
    runner.registry.register("lake3", conn)
    runner.execute("CREATE TABLE lake3.st (k BIGINT, v DOUBLE, "
                   "s VARCHAR) WITH (format = 'orc')")
    h = conn.get_table("st")
    tdir = conn._table_dir("st")
    table = pa.table({
        "k": pa.array(range(200_000), pa.int64()),
        "v": pa.array([float(i) for i in range(200_000)]),
        "s": pa.array([f"x{i:07d}" for i in range(200_000)])})
    po.write_table(table, os.path.join(tdir, "part-0.orc"),
                   stripe_size=1 << 16, compression="zlib")
    splits = conn.get_splits(h, 8)
    nstripes = len(splits)
    assert nstripes > 1                          # one split per stripe
    pruned = conn.prune_splits(h, splits, [("k", "lt", 10)])
    assert len(pruned) == 1                      # only the first stripe
    pruned = conn.prune_splits(h, splits, [("k", "ge", 199_999)])
    assert len(pruned) == 1                      # only the last stripe
    # varchar stats prune too
    pruned = conn.prune_splits(h, splits, [("s", "lt", "x0000005")])
    assert len(pruned) == 1
    # end-to-end: results unchanged with pruning in play
    got = runner.execute(
        "SELECT count(*), sum(v) FROM lake3.st WHERE k < 500").rows
    assert got == [(500, float(sum(range(500))))]


def test_orc_nested_schema_refuses_flat_stats_mapping(tmp_path):
    """A nested root field owns extra Type entries, so the flat
    'data column i <-> stats index i+1' mapping would read the WRONG
    column's min/max (e.g. column after a struct reads the struct's
    first child).  The parser must refuse (None -> no pruning) unless
    every root field is primitive (ADVICE r5)."""
    import os

    import pyarrow as pa
    import pyarrow.orc as po

    from presto_tpu.connectors.orcmeta import read_stripe_stats

    nested = os.path.join(str(tmp_path), "nested.orc")
    table = pa.table({
        "a": pa.array(range(100), pa.int64()),
        "st": pa.array([{"x": i, "y": float(i)} for i in range(100)],
                       pa.struct([("x", pa.int64()),
                                  ("y", pa.float64())])),
        "b": pa.array(range(1000, 1100), pa.int64())})
    po.write_table(table, nested, compression="zlib")
    assert read_stripe_stats(nested) is None
    # an all-primitive file keeps parsing
    flat = os.path.join(str(tmp_path), "flat.orc")
    po.write_table(pa.table({"a": pa.array(range(100), pa.int64())}),
                   flat, compression="zlib")
    st = read_stripe_stats(flat)
    assert st is not None
    assert st.stripe_column(0, "a")["min"] == 0


def test_orc_stripe_index_bound_checked():
    """A split enumerating more stripes than the parsed metadata covers
    must degrade to no-pruning (None), never an IndexError."""
    from presto_tpu.connectors.orcmeta import OrcFileStats

    st = OrcFileStats(["a"], [[{"min": 0, "max": 9, "has_null": False,
                                "n": 10}]])
    assert st.stripe_column(0, "a")["max"] == 9
    assert st.stripe_column(1, "a") is None
    assert st.stripe_column(-1, "a") is None
    assert st.stripe_column(0, "missing") is None
