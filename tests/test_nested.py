"""Array/map/row types: batch layer, serde, SQL functions, lambdas,
UNNEST, collect aggregates.

Reference models: nested blocks (presto-spi/.../block/ArrayBlock.java,
MapBlock.java, RowBlock.java), the array/map/lambda scalar library
(presto-main/.../operator/scalar/), UnnestOperator.java:39, and the
array_agg/map_agg/min_by accumulators."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import batch_from_pylist, concat_batches
from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.serde import deserialize_batch, serialize_batch

AB = T.ArrayType("array", element=T.BIGINT)
AS = T.ArrayType("array", element=T.VARCHAR)
MV = T.MapType("map", key=T.VARCHAR, value=T.BIGINT)
RW = T.RowType("row", field_names=("a", "b"),
               field_types=(T.BIGINT, T.VARCHAR))


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def q1(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestNestedBatch:
    ROWS = [
        ([1, 2, 3], {"x": 1}, (10, "p")),
        ([], {"y": 2, "z": 3}, (20, "q")),
        (None, None, None),
        ([7], {}, (30, "r")),
    ]

    def test_roundtrip_take_head_pad(self):
        b = batch_from_pylist([AB, MV, RW], self.ROWS)
        assert b.to_pylist() == self.ROWS
        assert b.take(np.array([3, 0])).to_pylist() == [self.ROWS[3],
                                                        self.ROWS[0]]
        assert b.head(2).to_pylist() == self.ROWS[:2]
        assert b.pad_rows(16).compact().to_pylist() == self.ROWS

    def test_concat(self):
        b = batch_from_pylist([AB, MV, RW], self.ROWS)
        c = concat_batches([b, b.head(1)])
        assert c.to_pylist() == self.ROWS + self.ROWS[:1]

    def test_serde_roundtrip(self):
        nested = T.ArrayType("array", element=AB)
        rows = [([["a"]], [[1, 2], [3]]), (None, []), ([[], ["b", "c"]],
                                                       [[4]])]
        b = batch_from_pylist(
            [T.ArrayType("array", element=AS), nested], rows)
        assert deserialize_batch(serialize_batch(b)).to_pylist() == rows

    def test_parse_display_roundtrip(self):
        for t in (AB, MV, RW, T.ArrayType("array", element=MV)):
            assert T.parse_type(t.display()) == t


class TestNestedSql:
    CASES = [
        ("select array[1,2,3]", ([1, 2, 3],)),
        ("select cardinality(array[1,2,3]), array[1,2,3][2]", (3, 2)),
        ("select element_at(array[1,2], 5)", (None,)),
        ("select element_at(array[1,2], -1)", (2,)),
        ("select contains(array[1,2], 2), contains(array[1,2], 9)",
         (True, False)),
        ("select array_position(array['a','b','c'], 'b')", (2,)),
        ("select array_min(array[3,1,2]), array_max(array[3,1,2])", (1, 3)),
        ("select array_distinct(array[1,1,2])", ([1, 2],)),
        ("select array_sort(array['c','a','b'])", (["a", "b", "c"],)),
        ("select reverse(array[1,2,3])", ([3, 2, 1],)),
        ("select array[1,2] || array[3]", ([1, 2, 3],)),
        ("select concat(array[1], array[2], array[3])", ([1, 2, 3],)),
        ("select array_join(array['x','y'], '-')", ("x-y",)),
        ("select slice(array[1,2,3,4,5], 2, 3)", ([2, 3, 4],)),
        ("select array_remove(array[1,2,1], 1)", ([2],)),
        ("select array_intersect(array[1,2,3], array[2,3,4])", ([2, 3],)),
        ("select array_union(array[1,2], array[2,3])", ([1, 2, 3],)),
        ("select array_except(array[1,2,3], array[2])", ([1, 3],)),
        ("select arrays_overlap(array[1,2], array[2,9])", (True,)),
        ("select flatten(array[array[1,2], array[3]])", ([1, 2, 3],)),
        ("select repeat('ab', 3)", (["ab", "ab", "ab"],)),
        ("select sequence(1, 5)", ([1, 2, 3, 4, 5],)),
        ("select sequence(5, 1, -2)", ([5, 3, 1],)),
        ("select split('a,b,c', ',')", (["a", "b", "c"],)),
        ("select split('a,b,c', ',', 2)", (["a", "b,c"],)),
        ("select map(array['k1','k2'], array[1,2])['k2']", (2,)),
        ("select map_keys(map(array['k'], array[1]))", (["k"],)),
        ("select map_values(map(array['k'], array[1]))", ([1],)),
        ("select cardinality(map(array['a','b'], array[1,2]))", (2,)),
        ("select element_at(map(array['a'], array[1]), 'zz')", (None,)),
        ("select map_concat(map(array['a'], array[1]), "
         "map(array['b'], array[2]))", ({"a": 1, "b": 2},)),
        ("select map_from_entries(array[row('x', 1), row('y', 2)])",
         ({"x": 1, "y": 2},)),
        ("select row(1, 'x')", ((1, "x"),)),
        ("select row(1, 'x')[1]", (1,)),
        ("select cast(null as array(bigint)) is null", (True,)),
        # lambdas
        ("select transform(array[1,2,3], x -> x * 10)", ([10, 20, 30],)),
        ("select filter(array[1,2,3,4], x -> x % 2 = 0)", ([2, 4],)),
        ("select reduce(array[1,2,3], 0, (s,x) -> s + x, s -> s)", (6,)),
        ("select any_match(array[1,2], x -> x > 1), "
         "all_match(array[1,2], x -> x > 1), "
         "none_match(array[1,2], x -> x > 5)", (True, False, True)),
        ("select map_filter(map(array['a','b'], array[1,2]), "
         "(k,v) -> v > 1)", ({"b": 2},)),
        ("select transform_values(map(array['a'], array[2]), "
         "(k,v) -> v * 3)", ({"a": 6},)),
    ]

    @pytest.mark.parametrize("sql,expected", CASES,
                             ids=[c[0][:60] for c in CASES])
    def test_scalar(self, runner, sql, expected):
        assert q1(runner, sql) == expected

    def test_lambda_capture(self, runner):
        sql = ("select transform(arr, x -> x + y) from "
               "(values (array[1,2], 10), (array[3], 100)) t(arr, y)")
        assert runner.execute(sql).rows == [([11, 12],), ([103],)]

    def test_nested_over_table_column(self, runner):
        sql = ("select o_orderkey, transform(sequence(1, o_orderkey), "
               "x -> x * 2) from orders where o_orderkey <= 3 "
               "order by o_orderkey")
        rows = runner.execute(sql).rows
        assert rows[0] == (1, [2])
        assert all(r[1] == [2 * i for i in range(1, r[0] + 1)]
                   for r in rows)


class TestUnnest:
    def test_standalone(self, runner):
        assert runner.execute(
            "select * from unnest(array[1,2,3])").rows == [(1,), (2,), (3,)]

    def test_ordinality(self, runner):
        assert runner.execute(
            "select * from unnest(array['a','b']) with ordinality"
        ).rows == [("a", 1), ("b", 2)]

    def test_map(self, runner):
        assert runner.execute(
            "select * from unnest(map(array['k1','k2'], array[10,20]))"
        ).rows == [("k1", 10), ("k2", 20)]

    def test_cross_join_lateral(self, runner):
        sql = ("select o_orderkey, tag from (select o_orderkey, "
               "array['p','q'] as tags from orders limit 2) "
               "cross join unnest(tags) as t(tag) order by o_orderkey, tag")
        rows = runner.execute(sql).rows
        assert len(rows) == 4
        assert rows[0][1] == "p" and rows[1][1] == "q"

    def test_array_of_rows(self, runner):
        assert runner.execute(
            "select * from unnest(array[row(1,'a'), row(2,'b')])"
        ).rows == [(1, "a"), (2, "b")]

    def test_zip_two_arrays(self, runner):
        assert runner.execute(
            "select * from unnest(array[1,2,3], array['x','y'])"
        ).rows == [(1, "x"), (2, "y"), (3, None)]

    def test_zip_with_empty_array(self, runner):
        # shorter array is EMPTY: gather index must stay in bounds
        assert runner.execute(
            "select * from unnest(array[1,2], array[])"
        ).rows == [(1, None), (2, None)]

    def test_left_join_unnest_preserves_outer_rows(self, runner):
        sql = ("select t.id, u.v from (values (1, array[7]), (2, array[]),"
               " (3, cast(null as array(bigint)))) t(id, arr) "
               "left join unnest(t.arr) as u(v) on true order by t.id")
        assert runner.execute(sql).rows == [(1, 7), (2, None), (3, None)]

    def test_left_join_unnest_ordinality_null_on_empty(self, runner):
        sql = ("select t.id, u.o from (values (1, array[7]), "
               "(2, array[])) t(id, arr) left join "
               "unnest(t.arr) with ordinality as u(v, o) on true "
               "order by t.id")
        assert runner.execute(sql).rows == [(1, 1), (2, None)]

    def test_unnest_split_roundtrip(self, runner):
        # split -> unnest -> array_agg: the classic pipeline
        sql = ("select array_agg(w) from (select w from "
               "unnest(split('a b c', ' ')) as t(w))")
        assert q1(runner, sql) == (["a", "b", "c"],)


class TestCollectAggregates:
    def test_array_agg_global(self, runner):
        assert q1(runner, "select array_agg(x) from (values (1),(2),(3)) "
                          "t(x)") == ([1, 2, 3],)

    def test_array_agg_grouped(self, runner):
        sql = ("select k, array_agg(v) from (values (1,'a'),(1,'b'),"
               "(2,'c')) t(k,v) group by k order by k")
        assert runner.execute(sql).rows == [(1, ["a", "b"]), (2, ["c"])]

    def test_array_agg_keeps_nulls(self, runner):
        assert q1(runner, "select array_agg(x) from (values (1),(null),"
                          "(3)) t(x)") == ([1, None, 3],)

    def test_map_agg(self, runner):
        assert q1(runner, "select map_agg(k, v) from (values ('x',1),"
                          "('y',2)) t(k,v)") == ({"x": 1, "y": 2},)

    def test_min_max_by(self, runner):
        assert q1(runner, "select min_by(name, price), max_by(name, price)"
                          " from (values ('a',3),('b',1),('c',9)) "
                          "t(name,price)") == ("b", "c")

    def test_array_agg_over_tpch(self, runner):
        sql = ("select o_orderpriority, cardinality(array_agg(o_orderkey))"
               ", count(*) from orders group by o_orderpriority")
        for _, card, cnt in runner.execute(sql).rows:
            assert card == cnt


class TestNestedPlanSerde:
    QUERIES = [
        "select transform(array[1,2], x -> x + o_orderkey) from orders "
        "where o_orderkey < 3",
        "select array_agg(o_orderkey) from orders group by o_orderpriority",
        "select t.v from orders cross join unnest(array[1,2]) as t(v) "
        "where o_orderkey = 1",
    ]

    @pytest.mark.parametrize("sql", QUERIES)
    def test_fragment_roundtrip(self, runner, sql):
        import json

        from presto_tpu.server.fragmenter import Fragmenter
        from presto_tpu.sql.optimizer import optimize
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.sql.planner import Metadata, Planner
        from presto_tpu.sql.planserde import (
            fragment_from_json, fragment_to_json,
        )

        metadata = Metadata(runner.registry, "tpch")
        logical = Planner(metadata).plan(parse_statement(sql))
        dplan = Fragmenter(metadata=metadata).fragment(
            optimize(logical, metadata))
        for frag in dplan.fragments:
            wire = json.dumps(fragment_to_json(frag))
            assert fragment_from_json(json.loads(wire)) == frag
