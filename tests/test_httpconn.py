"""HTTP connector: a real network protocol behind the connector SPI
(presto-example-http role — ExampleClient.java:41).  A live local HTTP
server serves the metadata document and CSV shards; SQL joins the
remote table against tpch."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from presto_tpu.connectors.httpconn import HttpConnector
from presto_tpu.localrunner import LocalQueryRunner

META = {
    "tables": [
        {"name": "numbers",
         "columns": [{"name": "label", "type": "varchar"},
                     {"name": "value", "type": "bigint"},
                     {"name": "weight", "type": "double"}],
         "sources": ["/numbers-1.csv", "/numbers-2.csv"]},
        {"name": "regions_http",
         "columns": [{"name": "r_regionkey", "type": "bigint"},
                     {"name": "tag", "type": "varchar"}],
         "sources": ["/regions.csv"]},
    ]
}

FILES = {
    "/meta.json": json.dumps(META).encode(),
    "/numbers-1.csv": b"one,1,0.5\ntwo,2,1.5\n",
    "/numbers-2.csv": b"three,3,2.5\n,,\nfive,5,4.5\n",
    "/regions.csv": b"0,alpha\n1,beta\n2,gamma\n3,delta\n4,epsilon\n",
}


@pytest.fixture(scope="module")
def server():
    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = FILES.get(self.path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_scan_over_http(server):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.registry.register("http", HttpConnector(f"{server}/meta.json"))
    rows = r.execute("SELECT label, value, weight FROM http.numbers "
                     "ORDER BY value").rows
    # the all-empty CSV record decodes as NULLs; nulls order last
    assert rows == [("one", 1, 0.5), ("two", 2, 1.5), ("three", 3, 2.5),
                    ("five", 5, 4.5), (None, None, None)]
    got = r.execute("SELECT sum(value), count(*) FROM http.numbers").rows
    assert got == [(11, 5)]


def test_multi_split_and_join_with_tpch(server):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.registry.register("http", HttpConnector(f"{server}/meta.json"))
    # each source URI is one split (P5 over network shards)
    conn = r.registry.get("http")
    assert len(conn.get_splits(conn.get_table("numbers"), 4)) == 2
    rows = r.execute(
        "SELECT n.tag, count(*) FROM tpch.nation t "
        "JOIN http.regions_http n ON t.n_regionkey = n.r_regionkey "
        "GROUP BY n.tag ORDER BY n.tag").rows
    assert len(rows) == 5 and all(c == 5 for _, c in rows)


def test_show_tables_lists_http_catalog(server):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.registry.register("http", HttpConnector(f"{server}/meta.json"))
    names = {row[0] for row in
             r.execute("SHOW TABLES FROM http").rows}
    assert {"numbers", "regions_http"} <= names
