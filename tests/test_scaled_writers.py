"""Distributed DML + scaled writers (P6).

The last SURVEY §2.13 parallelism strategy: INSERT/CTAS plan as query
fragments -> round-robin exchange -> a 'scaled'-partitioned writer
fragment whose task count follows the estimated volume
(SCALED_WRITER_DISTRIBUTION, SystemPartitioningHandle.java:62;
ScaledWriterScheduler.java:40) -> a single TableFinish fragment whose
one metadata transaction publishes every staged fragment atomically
(TableWriterOperator.java:58 / TableFinishOperator.java:46).
"""

import pytest

from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.connectors.raptor import RaptorConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.server.dqr import DistributedQueryRunner

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("raptor_dist"))

    def factory() -> ConnectorRegistry:
        reg = ConnectorRegistry()
        reg.register("tpch", TpchConnector(scale=0.01))
        # shared storage root: every in-process node sees the same shard
        # files + metadata db (the shared-filesystem deployment shape)
        reg.register("raptor", RaptorConnector(root))
        return reg

    import dataclasses

    from presto_tpu.config import DEFAULT

    # scale-out threshold small enough that SF0.01 volumes exercise it
    # (scaled_writer_rows_per_task session-steerable config)
    cfg = dataclasses.replace(DEFAULT, scaled_writer_rows_per_task=10_000)
    dqr = DistributedQueryRunner(factory, "tpch", n_workers=3, config=cfg)
    yield dqr
    dqr.close()


def _scaled_task_count(cluster, sql_fragment: str) -> int:
    """Distinct writer tasks the scheduler placed for the query whose
    text contains ``sql_fragment``."""
    for q in cluster.coordinator.queries.values():
        if sql_fragment in q.sql:
            scaled_fids = set()
            for f in getattr(q, "_dplan_fragments", []):
                pass
            tasks = {}
            for fid, task_id, _uri in q._placements:
                tasks.setdefault(fid, set()).add(task_id)
            # the writer fragment is the one whose task ids appear in
            # the plan text as 'scaled'
            for line in q.plan_text.splitlines():
                if "[scaled]" in line:
                    fid = int(line.split()[1])
                    return len(tasks.get(fid, ()))
    raise AssertionError(f"no query matching {sql_fragment!r}")


def test_bulk_insert_scales_writers(cluster):
    cluster.execute("CREATE TABLE raptor.li (okey bigint, qty double)")
    res = cluster.execute(
        "INSERT INTO raptor.li SELECT l_orderkey, l_quantity "
        "FROM tpch.lineitem")
    n = res.rows[0][0]
    assert n > 50_000
    got = cluster.execute(
        "SELECT count(*), sum(qty), min(okey), max(okey) "
        "FROM raptor.li").rows
    want = cluster.execute(
        "SELECT count(*), sum(l_quantity), min(l_orderkey), "
        "max(l_orderkey) FROM tpch.lineitem").rows
    assert got[0][0] == want[0][0] == n
    assert abs(got[0][1] - want[0][1]) < 1e-6 * abs(want[0][1])
    assert got[0][2:] == want[0][2:]
    # volume >> threshold: every worker got a writer task
    assert _scaled_task_count(cluster, "INSERT INTO raptor.li SELECT") == 3


def test_small_insert_single_writer(cluster):
    cluster.execute("CREATE TABLE raptor.small (a bigint)")
    res = cluster.execute(
        "INSERT INTO raptor.small VALUES (1), (2), (3)")
    assert res.rows[0][0] == 3
    assert sorted(r[0] for r in cluster.execute(
        "SELECT a FROM raptor.small").rows) == [1, 2, 3]
    assert _scaled_task_count(cluster, "raptor.small VALUES") == 1


def test_distributed_ctas(cluster):
    res = cluster.execute(
        "CREATE TABLE raptor.ords AS SELECT o_orderkey, o_totalprice "
        "FROM tpch.orders WHERE o_totalprice > 100000")
    n = res.rows[0][0]
    want = cluster.execute(
        "SELECT count(*) FROM tpch.orders "
        "WHERE o_totalprice > 100000").rows[0][0]
    assert n == want
    assert cluster.execute(
        "SELECT count(*) FROM raptor.ords").rows[0][0] == want


def test_staging_invisible_until_commit(tmp_path):
    """Atomicity invariant: task sinks stage shard files without
    publishing; only finish_write's metadata transaction makes rows
    visible (abandoned writes leave the table untouched)."""
    from presto_tpu.batch import batch_from_pylist
    from presto_tpu import types as T

    conn = RaptorConnector(str(tmp_path))
    r = LocalQueryRunner.tpch(scale=0.01)
    r.registry.register("raptor2", conn)
    r.execute("CREATE TABLE raptor2.t (a bigint)")
    h = conn.get_table("t")
    wid = conn.begin_write(h)
    sink = conn.task_sink(h, wid, "task-0")
    sink.append(batch_from_pylist([T.BIGINT], [(1,), (2,)]).to_device())
    assert sink.finish() == 2
    frag = sink.fragment()
    # staged but NOT committed: readers see nothing
    assert r.execute("SELECT count(*) FROM raptor2.t").rows == [(0,)]
    conn.finish_write(h, wid, [frag])
    assert r.execute("SELECT count(*) FROM raptor2.t").rows == [(2,)]
