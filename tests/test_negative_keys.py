"""Joins and semijoins over NEGATIVE key values.

Regression: the single-word id fast path used a fixed +2 shift, so any
key <= -3 collided with the dead-row sentinels and silently never
matched (and NOT IN wrongly retained rows present in the subquery).
Both tiers now shift by the build side's live minimum.
"""

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner.tpch(scale=0.01)
    r.execute("CREATE TABLE memory.neg_a (k BIGINT, v BIGINT)")
    r.execute("INSERT INTO memory.neg_a VALUES "
              "(-5, 1), (-3, 2), (0, 3), (7, 4), (NULL, 5)")
    r.execute("CREATE TABLE memory.neg_b (k BIGINT, w BIGINT)")
    r.execute("INSERT INTO memory.neg_b VALUES "
              "(-5, 10), (-1, 20), (7, 30), (NULL, 40)")
    return r


def test_inner_join_negative_keys(runner):
    got = sorted(runner.execute(
        "SELECT a.k, a.v, b.w FROM memory.neg_a a "
        "JOIN memory.neg_b b ON a.k = b.k").rows)
    assert got == [(-5, 1, 10), (7, 4, 30)]


def test_left_join_negative_keys(runner):
    got = sorted(runner.execute(
        "SELECT a.k, b.w FROM memory.neg_a a "
        "LEFT JOIN memory.neg_b b ON a.k = b.k").rows,
        key=lambda r: (r[0] is None, r[0]))
    assert got == [(-5, 10), (-3, None), (0, None), (7, 30), (None, None)]


def test_semi_anti_negative_keys(runner):
    got = sorted(r[0] for r in runner.execute(
        "SELECT v FROM memory.neg_a WHERE k IN "
        "(SELECT k FROM memory.neg_b WHERE k IS NOT NULL)").rows)
    assert got == [1, 4]
    # k=-3 is genuinely absent from b; k=-5 and 7 are present
    got = sorted(r[0] for r in runner.execute(
        "SELECT v FROM memory.neg_a WHERE k NOT IN "
        "(SELECT k FROM memory.neg_b WHERE k IS NOT NULL)").rows)
    assert got == [2, 3]


def test_group_by_negative_keys(runner):
    got = sorted(runner.execute(
        "SELECT k, count(*) FROM memory.neg_a GROUP BY k").rows,
        key=lambda r: (r[0] is None, r[0]))
    assert got == [(-5, 1), (-3, 1), (0, 1), (7, 1), (None, 1)]
