"""Native tier (C++ LZ4/XXH64) and Batch wire-serde tests.

Mirrors the reference's serde coverage: every Block encoding round-trips
(presto-spi block encoding tests) and PagesSerde compress/decompress
round-trips (presto-main/.../execution/buffer/TestPagesSerde.java)."""

import numpy as np
import pytest

from presto_tpu import native
from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Dictionary
from presto_tpu.serde import deserialize_batch, frame_size, serialize_batch


def test_native_builds():
    assert native.available()


def test_lz4_roundtrip_various():
    rng = np.random.default_rng(7)
    cases = [
        b"",
        b"a",
        b"abcd" * 3,
        bytes(100_000),                       # all zeros, highly compressible
        rng.bytes(100_000),                   # incompressible
        (b"the quick brown fox " * 4096),     # repetitive text
        rng.bytes(13) + bytes(50) + rng.bytes(13),
    ]
    for data in cases:
        c = native.lz4_compress(data)
        assert native.lz4_decompress(c, len(data)) == data


def test_lz4_compresses_repetitive_data():
    data = b"presto_tpu page " * 10_000
    assert len(native.lz4_compress(data)) < len(data) // 10


def test_lz4_fuzz_roundtrip():
    rng = np.random.default_rng(11)
    for _ in range(50):
        n = int(rng.integers(0, 5000))
        # Mix of random and repeated segments to exercise match emission.
        segs = []
        while sum(map(len, segs)) < n:
            if rng.random() < 0.5:
                segs.append(rng.bytes(int(rng.integers(1, 64))))
            else:
                segs.append(bytes(segs[-1] if segs else b"x") *
                            int(rng.integers(1, 8)))
        data = b"".join(segs)[:n]
        c = native.lz4_compress(data)
        assert native.lz4_decompress(c, len(data)) == data


def test_xxh64_reference_vectors():
    # Published xxHash64 test vectors (seed 0).
    assert native.xxh64(b"") == 0xEF46DB3751D8E999
    assert native.xxh64(b"a") == 0xD24EC4F1A98C6E5B
    assert native.xxh64(b"abc") == 0x44BC2CF5AD770999


def _sample_batch() -> Batch:
    dic = Dictionary(["AIR", "RAIL", "TRUCK"])
    n = 1000
    rng = np.random.default_rng(3)
    cols = (
        Column(T.BIGINT, rng.integers(0, 1 << 40, n).astype(np.int64)),
        Column(T.DOUBLE, rng.random(n)),
        Column(T.INTEGER, rng.integers(-5, 5, n).astype(np.int32),
               valid=rng.random(n) > 0.1),
        Column(T.VARCHAR, rng.integers(0, 3, n).astype(np.int32),
               dictionary=dic),
        Column(T.DecimalType("decimal", precision=15, scale=2),
               rng.integers(0, 10**6, n).astype(np.int64)),
        Column(T.DATE, rng.integers(8000, 11000, n).astype(np.int32)),
    )
    return Batch(cols, n)


@pytest.mark.parametrize("compress", [True, False])
def test_batch_serde_roundtrip(compress):
    batch = _sample_batch()
    wire = serialize_batch(batch, compress=compress)
    assert frame_size(wire) == len(wire)
    out = deserialize_batch(wire)
    assert out.num_rows == batch.num_rows
    assert out.num_columns == batch.num_columns
    for a, b in zip(batch.columns, out.columns):
        assert a.type.display() == b.type.display()
        np.testing.assert_array_equal(np.asarray(a.values), b.values)
        if a.valid is None:
            assert b.valid is None
        else:
            np.testing.assert_array_equal(np.asarray(a.valid), b.valid)
        if a.dictionary is not None:
            assert a.dictionary.values == b.dictionary.values
    assert batch.to_pylist() == out.to_pylist()


def test_batch_serde_drops_padding():
    batch = _sample_batch().pad_rows(4096)
    out = deserialize_batch(serialize_batch(batch))
    assert out.num_rows == batch.num_rows
    assert out.capacity == batch.num_rows


def test_serde_checksum_detects_corruption():
    wire = bytearray(serialize_batch(_sample_batch()))
    wire[len(wire) // 2] ^= 0xFF
    with pytest.raises(ValueError):
        deserialize_batch(bytes(wire))


def test_empty_batch_roundtrip():
    batch = Batch((Column(T.BIGINT, np.zeros(0, np.int64)),), 0)
    out = deserialize_batch(serialize_batch(batch))
    assert out.num_rows == 0
    assert out.num_columns == 1
