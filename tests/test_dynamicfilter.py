"""Dynamic filtering (DynamicFilterSourceOperator role): build-side key
domains prune probe rows; results must match the unfiltered path."""

import dataclasses

import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def on_runner():
    return LocalQueryRunner.tpch(scale=0.01)


@pytest.fixture(scope="module")
def off_runner():
    cfg = dataclasses.replace(DEFAULT, dynamic_filtering_enabled=False)
    return LocalQueryRunner.tpch(scale=0.01, config=cfg)


QUERIES = [
    # selective build side: most probe rows should be pruned pre-join
    """select count(*), sum(l_extendedprice) from lineitem, orders
       where l_orderkey = o_orderkey and o_totalprice > 400000""",
    """select count(*) from lineitem, part
       where l_partkey = p_partkey and p_size = 50""",
    # multi-key join
    """select count(*) from lineitem l1, lineitem l2
       where l1.l_orderkey = l2.l_orderkey
       and l1.l_linenumber = l2.l_linenumber and l2.l_quantity > 49""",
    # empty build side
    """select count(*) from lineitem, orders
       where l_orderkey = o_orderkey and o_totalprice < 0""",
]



def _pair_key(r):
    """Sort key that pairs rows robustly across float summation-order
    noise: floats participate rounded, so nearly-equal rows sort
    identically on both sides."""
    return tuple(
        (1, round(v, 4)) if isinstance(v, float)
        else (2, "") if v is None
        else (0, str(v))
        for v in r)


@pytest.mark.parametrize("sql", QUERIES)
def test_results_identical(on_runner, off_runner, sql):
    a = on_runner.execute(sql).rows
    b = off_runner.execute(sql).rows
    assert len(a) == len(b)
    for ra, rb in zip(sorted(a, key=_pair_key), sorted(b, key=_pair_key)):
        for va, vb in zip(ra, rb):
            if isinstance(va, float):
                # concurrent feed drivers change float summation order
                assert va == pytest.approx(vb, rel=1e-9), (ra, rb)
            else:
                assert va == vb, (ra, rb)


def test_filter_actually_prunes(on_runner):
    from presto_tpu.exec.dynamicfilter import DynamicFilter
    import numpy as np
    from presto_tpu.batch import batch_from_pylist
    from presto_tpu import types as T

    dyn = DynamicFilter(1)
    build = batch_from_pylist([T.BIGINT], [(5,), (7,), (9,)])
    dyn.fill_from_build(build, [0])
    assert dyn.ready
    assert dyn.mins[0] == 5 and dyn.maxs[0] == 9
    assert list(dyn.sets[0]) == [5, 7, 9]


def test_filter_placed_at_scan(on_runner):
    """The runtime filter must sit directly after the probe TableScan
    (channel provenance through FilterProject), not just before the join
    (LocalDynamicFilter pushes to the scan in the reference).  With
    pipeline fusion on (the default) the filter is the first stage of a
    fused segment riding on the scan — same placement, one dispatch."""
    from presto_tpu.exec.dynamicfilter import DynamicFilterOperatorFactory
    from presto_tpu.exec.fusion import DFStage, FusedSegmentOperatorFactory
    from presto_tpu.exec.operators import TableScanOperatorFactory
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Metadata, Planner

    def holds_df(f):
        if isinstance(f, DynamicFilterOperatorFactory):
            return True
        return isinstance(f, FusedSegmentOperatorFactory) and \
            isinstance(f.stages[0], DFStage)

    md = Metadata(on_runner.registry, "tpch")
    sql = ("select o_orderpriority, l_quantity from orders join lineitem "
           "on o_orderkey = l_orderkey where l_quantity > 45")
    plan = optimize(Planner(md).plan(parse_statement(sql)), md)
    phys = PhysicalPlanner(on_runner.registry).plan(plan)
    probe = [p for p in phys.pipelines
             if any(holds_df(f) for f in p.factories)]
    assert probe, "no dynamic filter in any pipeline"
    factories = probe[0].factories
    i = next(idx for idx, f in enumerate(factories) if holds_df(f))
    assert isinstance(factories[i - 1], TableScanOperatorFactory)


def test_semijoin_dynamic_filter(on_runner, off_runner):
    sql = ("select count(*) from lineitem where l_orderkey in "
           "(select o_orderkey from orders where o_totalprice > 400000)")
    assert on_runner.execute(sql).rows == off_runner.execute(sql).rows
