"""Dynamic filtering (DynamicFilterSourceOperator role): build-side key
domains prune probe rows; results must match the unfiltered path."""

import dataclasses

import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def on_runner():
    return LocalQueryRunner.tpch(scale=0.01)


@pytest.fixture(scope="module")
def off_runner():
    cfg = dataclasses.replace(DEFAULT, dynamic_filtering_enabled=False)
    return LocalQueryRunner.tpch(scale=0.01, config=cfg)


QUERIES = [
    # selective build side: most probe rows should be pruned pre-join
    """select count(*), sum(l_extendedprice) from lineitem, orders
       where l_orderkey = o_orderkey and o_totalprice > 400000""",
    """select count(*) from lineitem, part
       where l_partkey = p_partkey and p_size = 50""",
    # multi-key join
    """select count(*) from lineitem l1, lineitem l2
       where l1.l_orderkey = l2.l_orderkey
       and l1.l_linenumber = l2.l_linenumber and l2.l_quantity > 49""",
    # empty build side
    """select count(*) from lineitem, orders
       where l_orderkey = o_orderkey and o_totalprice < 0""",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_results_identical(on_runner, off_runner, sql):
    a = on_runner.execute(sql).rows
    b = off_runner.execute(sql).rows
    assert a == b


def test_filter_actually_prunes(on_runner):
    from presto_tpu.exec.dynamicfilter import DynamicFilter
    import numpy as np
    from presto_tpu.batch import batch_from_pylist
    from presto_tpu import types as T

    dyn = DynamicFilter(1)
    build = batch_from_pylist([T.BIGINT], [(5,), (7,), (9,)])
    dyn.fill_from_build(build, [0])
    assert dyn.ready
    assert dyn.mins[0] == 5 and dyn.maxs[0] == 9
    assert list(dyn.sets[0]) == [5, 7, 9]
