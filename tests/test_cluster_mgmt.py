"""Cluster management tests: memory info/low-memory killer, graceful
shutdown, cluster size monitor (ClusterMemoryManager.java:173-347,
TotalReservationLowMemoryKiller, GracefulShutdownHandler,
ClusterSizeMonitor roles)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.server.coordinator import CoordinatorServer
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.server.worker import WorkerServer

pytestmark = pytest.mark.slow



def _factory(scale=0.01):
    def factory():
        reg = ConnectorRegistry()
        reg.register("tpch", TpchConnector(scale=scale))
        return reg

    return factory


def test_worker_memory_endpoint():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        dqr.execute("SELECT l_returnflag, sum(l_quantity) FROM lineitem "
                    "GROUP BY l_returnflag")
        infos = []
        for w in dqr.workers:
            with urllib.request.urlopen(f"{w.uri}/v1/memory",
                                        timeout=5) as resp:
                infos.append(json.loads(resp.read()))
        assert all("reserved" in i and "queries" in i for i in infos)
        # at least one worker recorded nonzero peak for the query's tasks
        assert any(
            q["peak"] > 0 for i in infos for q in i["queries"].values())


def test_graceful_shutdown_excludes_worker():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        co = dqr.coordinator
        for _ in range(40):
            if len(co.nodes.alive_nodes()) == 2:
                break
            time.sleep(0.1)
        assert len(co.nodes.alive_nodes()) == 2
        victim = dqr.workers[0]
        req = urllib.request.Request(
            f"{victim.uri}/v1/info/state", data=b'"SHUTTING_DOWN"',
            method="PUT")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["state"] == "SHUTTING_DOWN"
        # draining worker refuses new tasks
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{victim.uri}/v1/task/x", data=b"{}", method="POST",
                headers={"Content-Type": "application/json"}), timeout=5)
        assert ei.value.code == 503
        # heartbeat drops it from the schedulable set, queries still run
        for _ in range(60):
            if len(co.nodes.alive_nodes()) == 1:
                break
            time.sleep(0.1)
        assert len(co.nodes.alive_nodes()) == 1
        got = dqr.execute("SELECT count(*) FROM nation").rows
        assert got == [(25,)]


def test_cluster_size_monitor_blocks_until_workers():
    co = CoordinatorServer(_factory()(), "tpch", min_workers=1,
                           min_workers_wait_s=5.0)
    try:
        w = WorkerServer(_factory()(), node_id="late-worker")
        try:
            # announce AFTER the query is submitted: the size monitor
            # must wait for the worker instead of failing immediately
            import base64
            import threading

            def announce_later():
                time.sleep(0.8)
                body = json.dumps({"nodeId": w.node_id,
                                   "uri": w.uri}).encode()
                urllib.request.urlopen(urllib.request.Request(
                    f"{co.uri}/v1/announcement", data=body,
                    method="POST"), timeout=5).read()

            threading.Thread(target=announce_later, daemon=True).start()
            from presto_tpu.client import StatementClient

            cols, data = StatementClient(co.uri).execute(
                "SELECT count(*) FROM nation")
            assert data == [[25]]
        finally:
            w.close()
    finally:
        co.close()


def test_cluster_size_monitor_times_out():
    co = CoordinatorServer(_factory()(), "tpch", min_workers=1,
                           min_workers_wait_s=0.3)
    try:
        from presto_tpu.client import QueryFailed, StatementClient

        with pytest.raises(QueryFailed, match="[Ii]nsufficient"):
            StatementClient(co.uri).execute("SELECT count(*) FROM nation")
    finally:
        co.close()


def test_low_memory_killer():
    """Force a tiny cluster memory limit; a memory-hungry query must be
    killed with the out-of-memory message."""
    import presto_tpu.server.task as task_mod

    with DistributedQueryRunner.tpch(scale=0.05, n_workers=2) as dqr:
        co = dqr.coordinator
        co.cluster_memory_limit_bytes = 1  # anything trips the killer
        co._memory_thread = __import__("threading").Thread(
            target=co._memory_loop, args=(0.05,), daemon=True)
        co._memory_thread.start()
        from presto_tpu.client import QueryFailed

        with pytest.raises(QueryFailed, match="out of memory"):
            dqr.execute(
                "SELECT l_orderkey, l_partkey, sum(l_extendedprice) "
                "FROM lineitem, orders WHERE l_orderkey = o_orderkey "
                "GROUP BY l_orderkey, l_partkey ORDER BY 3 DESC LIMIT 5")
        co._memory_stop.set()


def test_shutdown_gracefully_waits_for_consumers():
    """shutdown_gracefully must not destroy buffered output a consumer
    has not fetched yet (drain completeness)."""
    import threading

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        # run a query fully, then drain a worker; buffers are acked so
        # the drain completes promptly
        assert dqr.execute("SELECT count(*) FROM nation").rows == [(25,)]
        w = dqr.workers[0]
        t0 = time.time()
        w.shutdown_gracefully(drain_timeout_s=10.0)
        assert time.time() - t0 < 10.0
        dqr.workers = dqr.workers[1:]  # already closed
        # remaining worker still serves queries
        assert dqr.execute("SELECT count(*) FROM region").rows == [(5,)]


def test_schedule_fails_over_draining_worker():
    """A worker that started draining after the scheduling snapshot
    answers 503; the coordinator retries on another worker."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        co = dqr.coordinator
        for _ in range(40):
            if len(co.nodes.alive_nodes()) == 2:
                break
            time.sleep(0.1)
        # flip draining directly (no heartbeat latency) so the
        # coordinator still schedules to it and must fail over
        dqr.workers[0].draining = True
        got = dqr.execute("SELECT l_returnflag, count(*) FROM lineitem "
                          "GROUP BY l_returnflag ORDER BY 1").rows
        assert [r[0] for r in got] == ["A", "N", "R"]


def test_topology_aware_ordering():
    """Consecutive tasks land in alternating topology domains
    (TopologyAwareNodeSelector.java:50 role)."""
    from presto_tpu.server.coordinator import NodeManager

    nm = NodeManager(interval_s=60)
    try:
        nm.announce("a1", "uri-a1", "rackA")
        nm.announce("a2", "uri-a2", "rackA")
        nm.announce("b1", "uri-b1", "rackB")
        nm.announce("b2", "uri-b2", "rackB")
        ordered = nm.topology_ordered(nm.alive_nodes())
        racks = ["A" if n.startswith("a") else "B" for n, _ in ordered]
        assert racks == ["A", "B", "A", "B"], ordered
    finally:
        nm.close()
