"""Spooled exchange tier (server/spool.py): cascade-free stage retry,
graceful worker drain, non-leaf speculation, eviction, GC, and the
fault-policy fallbacks.

The acceptance proofs:

- a worker lost AFTER its tasks finished costs ZERO re-execution:
  consumers (including the coordinator's root drain) repoint at the
  spool and resume at their current token;
- a worker lost MID-RUN re-runs only its own unfinished tasks — the
  producer subtree is read back from the spool, never re-computed
  (``producer_reruns_total == 0``);
- a worker drained mid-query exits the cluster without failing the
  query, pinned by exact rows + a WorkerDrainEvent;
- acked+spooled pages evicted under ``max_buffer_bytes`` pressure
  re-serve from the spool byte-exact on a late re-fetch;
- spool chaos (read-error / missing-object) retries or falls back to
  PR 5 cascading retry;
- a query's spool directory is GC'd at completion and orphans are swept
  at coordinator start.
"""

import dataclasses
import json
import os
import threading
import time
import urllib.request

import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.server.faults import FaultInjector
from presto_tpu.server.spool import FileSystemSpoolStore

pytestmark = pytest.mark.chaos


def _wait_nodes(co, n, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(co.nodes.alive_nodes()) == n:
            return
        time.sleep(0.02)
    raise AssertionError(f"cluster never reached {n} nodes")


def _spool_cfg(tmp_path, **over):
    return dataclasses.replace(
        DEFAULT, exchange_spooling_enabled=True,
        exchange_spool_path=str(tmp_path / "spool"),
        task_recovery_interval_s=0.05, **over)


# -- unit tier: the store and the buffer ------------------------------------

def test_spool_store_roundtrip(tmp_path):
    store = FileSystemSpoolStore(str(tmp_path / "s"))
    tid = "q1.2.0a1"
    store.write_page(tid, 0, 0, b"page-zero")
    store.write_page(tid, 0, 1, b"page-one")
    assert not store.is_complete(tid, 1)     # no COMPLETE marker yet
    store.set_complete(tid, 0, 2)
    assert store.is_complete(tid, 1)
    pages, nxt, complete = store.get_pages(tid, 0, 0)
    assert pages == [b"page-zero", b"page-one"]
    assert (nxt, complete) == (2, True)
    # resume mid-stream: same attempt, same tokens
    pages, nxt, complete = store.get_pages(tid, 0, 1)
    assert pages == [b"page-one"] and complete
    # counters moved
    assert store.stats["bytes_written"] == len(b"page-zero") + \
        len(b"page-one")
    assert store.stats["pages_read"] >= 3
    # GC: the whole query directory goes at once
    assert store.delete_query("q1")
    assert store.get_pages(tid, 0, 0) == ([], 0, False)


def test_spool_store_orphan_sweep_age_guard(tmp_path):
    store = FileSystemSpoolStore(str(tmp_path / "s"))
    store.write_page("old.0.0", 0, 0, b"x")
    store.write_page("new.0.0", 0, 0, b"y")
    old_dir = os.path.join(store.root, "old")
    os.utime(old_dir, (time.time() - 7200, time.time() - 7200))
    # only the stale query dir is swept; fresh ones (another cluster's
    # live query on a shared root) survive
    assert store.sweep_orphans(max_age_s=3600) == 1
    assert not os.path.exists(old_dir)
    assert os.path.exists(os.path.join(store.root, "new"))


def test_buffer_eviction_respools_exact_bytes(tmp_path):
    """Acked+spooled pages are evicted at max_buffer_bytes and re-served
    from the spool on a late re-fetch (the root-drain DISCARD/re-pull
    shape), byte-exact."""
    from presto_tpu.server.buffers import OutputBufferManager

    store = FileSystemSpoolStore(str(tmp_path / "s"))
    pages = [bytes([i]) * 100 for i in range(10)]
    mgr = OutputBufferManager(1, max_buffer_bytes=250, spool=store,
                              task_id="q2.0.0")
    for p in pages:
        mgr.enqueue(0, p)          # never blocks: eviction makes room
    mgr.set_no_more_pages()
    assert mgr.pages_spooled == 10
    assert mgr.pages_evicted >= 8          # memory held at most 2 pages
    assert mgr.bytes_evicted == 100 * mgr.pages_evicted
    assert mgr._bytes <= 250
    # late re-fetch from token 0: the evicted prefix re-serves from the
    # spool (and the spool holds the whole stream, so the re-fetch can
    # run to completion without touching memory)
    got, nxt, complete = mgr.get_pages(0, 0, max_bytes=1 << 20)
    while not complete:
        more, nxt, complete = mgr.get_pages(0, nxt, max_bytes=1 << 20)
        got.extend(more)
    assert got == pages and nxt == 10
    # a bounded re-fetch of just the evicted prefix is byte-exact too
    some, nxt2, _ = mgr.get_pages(0, 0, max_bytes=150)
    assert some == pages[:1] and nxt2 == 1
    # the whole output is durable: the spooled drain condition
    assert mgr.spooled_complete()


def test_spool_fault_policies(tmp_path):
    """read-error-n-times raises then clears; missing-object persists;
    HTTP rules never leak onto the spool path."""
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    store = FileSystemSpoolStore(str(tmp_path / "s"), injector=inj)
    store.write_page("q3.0.0", 0, 0, b"z")
    store.set_complete("q3.0.0", 0, 1)
    rule = inj.add_spool_rule(r"q3\.0\.0", policy="spool-read-error",
                              times=2)
    with pytest.raises(OSError):
        store.get_pages("q3.0.0", 0, 0)
    with pytest.raises(OSError):
        store.get_pages("q3.0.0", 0, 0)
    assert rule.remaining == 0
    assert store.get_pages("q3.0.0", 0, 0)[0] == [b"z"]   # recovered
    inj.add_spool_rule(r"q3\.0\.0", policy="spool-missing")
    with pytest.raises(FileNotFoundError):
        store.is_complete("q3.0.0", 1)
    # the HTTP drop-connection rule fired zero times on the spool path
    assert all(m != "SPOOL" or p != "drop-connection"
               for _, m, p in inj.injections)


# -- cluster tier -----------------------------------------------------------

def _drain_hold_injector():
    """Hold the coordinator's root-result drain (client-side) so worker
    tasks finish while the query is still in flight — the deterministic
    window every spool scenario below kills or drains a worker in."""
    inj = FaultInjector()
    rule = inj.add_rule(r"/results/", method="GET", policy="slow-task")
    return inj, rule


def _root_worker(q, dqr):
    """(index, uri) of the worker hosting the root gather task."""
    root_fid = q._dplan.root_fragment_id
    uri = next(u for f, _, u in q._placements if f == root_fid)
    idx = next(i for i, w in enumerate(dqr.workers) if w.uri == uri)
    return idx, uri


def _all_finished_and_spooled(worker, qid) -> bool:
    tasks = [t for t in worker.task_manager.tasks.values()
             if t.task_id.startswith(qid + ".")]
    return bool(tasks) and all(
        t.state == "FINISHED" and t.buffers.spooled_complete()
        for t in tasks)


def _wait_all_spooled(co, dqr, timeout_s=60.0) -> str:
    """Block until every task of the (single) in-flight query finished
    producing and its whole output is durable in the spool — the
    deterministic precondition for every kill-after-finish scenario.
    Asserts instead of racing on when the machine is loaded."""
    deadline = time.monotonic() + timeout_s
    qid = None
    while time.monotonic() < deadline:
        if co.queries and qid is None:
            qid = list(co.queries)[0]
        if qid:
            # scheduling places producers first: the producer tasks can
            # finish+spool before the ROOT task even exists, so require
            # the root placement too or the caller races on it
            q = co.queries[qid]
            root_placed = q._dplan is not None and any(
                f == q._dplan.root_fragment_id
                for f, _, _ in q._placements)
            if root_placed and all(_all_finished_and_spooled(w, qid)
                                   for w in dqr.workers):
                return qid
        time.sleep(0.02)
    raise AssertionError("tasks never reached finished+spooled")


def _tpch_oracle(sql, scale=0.01):
    from presto_tpu.localrunner import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=scale).execute(sql).rows


def test_worker_killed_after_finish_zero_reruns(tmp_path):
    """The headline: every task of the victim FINISHED and spooled
    before the kill — recovery repoints consumers (including the root
    drain, mid-stream) at the spool; NOTHING re-runs: no stage retry
    round, no new attempt ids, producer_reruns == 0, exact rows."""
    sql = ("select l_returnflag, count(*) from lineitem "
           "group by l_returnflag")
    want = _tpch_oracle(sql)
    cfg = _spool_cfg(tmp_path)
    inj, hold = _drain_hold_injector()
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=inj,
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(sql).rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # wait until EVERY task everywhere finished + spooled (the
        # held drain keeps the query in flight)
        qid = _wait_all_spooled(co, dqr)
        q = co.queries[qid]
        victim_idx, victim_uri = _root_worker(q, dqr)
        dqr.kill_worker(victim_idx)
        # recovery must move the root drain to the spool; then release
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and not q._spool_moves:
            time.sleep(0.02)
        assert q._spool_moves, "root drain never repointed at the spool"
        hold.release()
        t.join(timeout=60)
        assert not t.is_alive(), "query hung after worker death"
        assert "err" not in res, res
        assert sorted(res["rows"]) == sorted(want)
        # zero re-execution anywhere
        assert q.stage_retry_rounds == 0
        assert q.producer_reruns_total == 0
        assert all("a" not in tid.rsplit(".", 1)[-1]
                   for _, tid, _ in q._placements), q._placements
        assert all(u != victim_uri for _, _, u in q._placements)


def test_worker_killed_mid_run_restarts_alone_zero_producer_reruns(
        tmp_path):
    """Kill the victim while its tasks still run (results withheld, the
    PR 5 scenario) with spooling ON: only the victim's own unfinished
    tasks re-run — their producers are read back from the spool, so
    producer_reruns stays 0 and rows stay exact."""
    cfg = _spool_cfg(tmp_path)
    inj = FaultInjector()   # victim withholds results => query in flight
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select n_name, count(*) from nation join region "
                    "on n_regionkey = r_regionkey group by n_name").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        victim_uri = dqr.workers[1].uri
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and any(u == victim_uri
                          for _, _, u in qs[0]._placements):
                break
            time.sleep(0.02)
        q = list(co.queries.values())[0]
        dqr.kill_worker(1)
        t.join(timeout=120)
        assert not t.is_alive(), "query hung after worker death"
        assert "err" not in res, res
        assert sorted(res["rows"]) == sorted(
            (n, 1) for n, in dqr.execute(
                "select n_name from nation").rows)
        # the cascade-free guarantee: whatever re-ran, it was never a
        # producer of a lost stage
        assert q.producer_reruns_total == 0
        assert all(u != victim_uri for _, _, u in q._placements)


def test_graceful_drain_mid_query_exact_rows_and_event(tmp_path):
    """PUT /v1/info/state=SHUTTING_DOWN on the worker holding the root
    task mid-query: its tasks finish, the coordinator repoints the
    drain at the spool and releases the worker (WorkerDrainEvent), the
    worker leaves the cluster, and the query stays exact."""
    from presto_tpu.events import EventListener

    cfg = _spool_cfg(tmp_path)
    inj, hold = _drain_hold_injector()

    class DrainRecorder(EventListener):
        events = []

        def worker_drain(self, e):
            self.events.append(e)

    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=inj,
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=3) as dqr:
        co = dqr.coordinator
        dqr.event_bus.register(DrainRecorder())
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select count(*) from lineitem").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # wait until EVERY task everywhere finished + spooled (the
        # held drain keeps the query in flight)
        qid = _wait_all_spooled(co, dqr)
        q = co.queries[qid]
        victim_idx, victim_uri = _root_worker(q, dqr)
        victim = dqr.workers[victim_idx]
        victim.drain_grace_s = 0.3
        req = urllib.request.Request(
            f"{victim.uri}/v1/info/state", data=b'"SHUTTING_DOWN"',
            method="PUT")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["state"] == "SHUTTING_DOWN"
        # the coordinator hands the victim's tasks to the spool and the
        # worker's background drain closes it
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and \
                victim_uri not in q._drained_uris:
            time.sleep(0.02)
        assert victim_uri in q._drained_uris, "drain tick never released"
        hold.release()
        t.join(timeout=60)
        assert not t.is_alive(), "query hung during graceful drain"
        assert "err" not in res, res
        assert res["rows"] == [(59785,)]
        assert q.producer_reruns_total == 0
        # the drain event fired with the moved tasks + trace token
        assert DrainRecorder.events
        ev0 = DrainRecorder.events[0]
        assert ev0.worker_uri == victim_uri
        assert ev0.trace_token == q.trace_token
        assert ev0.task_ids
        # the worker really left: its HTTP plane goes dark
        deadline = time.monotonic() + 20.0
        gone = False
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(f"{victim_uri}/v1/info",
                                       timeout=1)
            except Exception:  # noqa: BLE001 - closed = unreachable
                gone = True
                break
            time.sleep(0.05)
        assert gone, "drained worker never shut down"
        dqr.workers = [w for i, w in enumerate(dqr.workers)
                       if i != victim_idx]


def test_spool_missing_object_falls_back_to_cascading_retry(tmp_path):
    """Spool verification faulted (missing-object on the coordinator's
    store): recovery falls back to PR 5 cascading stage retry — the
    query survives with exact rows, paying producer re-runs."""
    cfg = _spool_cfg(tmp_path)
    co_inj = FaultInjector()
    co_inj.add_spool_rule(r".", policy="spool-missing")
    # hold the root drain so the kill deterministically lands while the
    # query is in flight (under load, the killer thread can otherwise
    # lose the race and the query completes without any recovery)
    hold = co_inj.add_rule(r"/results/", method="GET",
                           policy="slow-task")
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=co_inj,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select n_name, count(*) from nation join region "
                    "on n_regionkey = r_regionkey group by n_name").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        victim_uri = dqr.workers[1].uri
        # wait for the CONDITION the kill is meant to hit — a NON-LEAF
        # task actually scheduled on the victim — instead of assuming a
        # wall-clock budget covers admission+planning+scheduling.  The
        # old wait checked only "any task on the victim" and fell
        # through SILENTLY on timeout: under a loaded full-suite run
        # the kill then landed before (or without) a non-leaf placement
        # and recovery was pure leaf-reschedule — no stage retry, and
        # the >=1 assertion flaked.  The victim's /results/ always
        # drop (injector), so its non-leaf output can never have been
        # consumed pre-kill: the fallback MUST cascade into stage
        # retry once the death is seen.
        deadline = time.monotonic() + 60.0

        def victim_has_nonleaf():
            qs = list(co.queries.values())
            if not qs:
                return False
            q0 = qs[0]
            with q0._recovery_lock:
                placements = list(q0._placements)
                specs = dict(q0._task_specs)
            return any(u == victim_uri and specs.get(t, {}).get("remote")
                       for _, t, u in placements)

        while time.monotonic() < deadline and not victim_has_nonleaf():
            time.sleep(0.02)
        assert victim_has_nonleaf(), \
            "no non-leaf task ever scheduled onto the victim"
        q = list(co.queries.values())[0]
        dqr.kill_worker(1)
        # release the drain only once the failure detector actually
        # sees the death: recovery (and its spool-verification
        # fallback) then deterministically runs while the query is
        # still in flight
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and \
                victim_uri not in co.nodes.dead_uris():
            time.sleep(0.02)
        assert victim_uri in co.nodes.dead_uris()
        hold.release()
        t.join(timeout=120)
        assert not t.is_alive()
        assert "err" not in res, res
        assert len(res["rows"]) == 25
        # the fallback really cascaded (and the fault really fired)
        assert q.stage_retry_rounds >= 1
        assert any(m == "SPOOL" for _, m, _ in co_inj.injections)


def test_spool_read_error_retried_by_consumer(tmp_path):
    """Transient spool read errors retry on the error-budget discipline
    instead of failing the drain; rows stay exact."""
    cfg = _spool_cfg(tmp_path)
    co_inj, hold = _drain_hold_injector()
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=co_inj,
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select count(*) from lineitem").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # wait until EVERY task everywhere finished + spooled (the
        # held drain keeps the query in flight)
        qid = _wait_all_spooled(co, dqr)
        q = co.queries[qid]
        victim_idx, _uri = _root_worker(q, dqr)
        dqr.kill_worker(victim_idx)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and not q._spool_moves:
            time.sleep(0.02)
        assert q._spool_moves
        # NOW fault the coordinator's spool reads: the root drain must
        # retry through them (the faults fire on the first two reads)
        rule = co_inj.add_spool_rule(r".", policy="spool-read-error",
                                     times=2)
        hold.release()
        t.join(timeout=60)
        assert not t.is_alive()
        assert "err" not in res, res
        assert res["rows"] == [(59785,)]
        assert rule.remaining == 0      # both faults really fired


def test_nonleaf_speculation_with_spool(tmp_path):
    """Non-leaf speculation, legal only with the spooled exchange: a
    held PROBE task (fragment 1 of a broadcast join — it consumes the
    broadcast build, so PR 5 refused to clone it) gets a clone that
    reads the build back from the spool (token 0, no buffer race), wins
    the race under a new attempt id, and the query stays exact."""
    cfg = _spool_cfg(
        tmp_path, speculative_execution_enabled=True,
        speculation_min_runtime_s=0.3, speculation_lag_factor=2.0)
    inj = FaultInjector()
    # hold ONLY the non-leaf probe task {qid}.1.0's results drain —
    # placed on worker 0 (first in topology order); its clone lands on
    # worker 1, whose injector-free drain must win the race
    rules = [inj.add_slow_task(r"\.1\.0")]
    try:
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=2, config=cfg,
                worker_injectors={0: inj},
                heartbeat_interval_s=0.05) as dqr:
            co = dqr.coordinator
            _wait_nodes(co, 2)
            res = {}

            sql = ("select n_name, count(*) from nation join region "
                   "on n_regionkey = r_regionkey group by n_name")
            want = _tpch_oracle(sql)

            def run():
                try:
                    res["rows"] = dqr.execute(sql).rows
                except Exception as e:  # noqa: BLE001
                    res["err"] = e

            t = threading.Thread(target=run)
            t.start()
            deadline = time.monotonic() + 30.0
            q = None
            won = None
            while time.monotonic() < deadline:
                qs = list(co.queries.values())
                if qs:
                    q = qs[0]
                    won = [tid for tid, sp in q._speculations.items()
                           if sp["state"] == "won"]
                    if won:
                        break
                time.sleep(0.02)
            assert won, (q._speculations if q else "no query")
            # the winning clone is a NON-leaf task (final agg, frag 1)
            assert won[0].split(".")[1] == "1", won
            for r in rules:
                r.release()
            t.join(timeout=60)
            assert not t.is_alive(), "query hung after speculation"
            assert "err" not in res, res
            assert sorted(res["rows"]) == sorted(want)
            clone = q._speculations[won[0]]["clone"]
            assert clone.endswith("a1")
            assert any(tid == clone for _, tid, _ in q._placements)
    finally:
        inj.release_all()


def test_spool_gc_on_completion_and_orphan_sweep(tmp_path):
    """No leaked spool files: a finished query's directory is deleted,
    and a stale orphan left behind is swept at coordinator start."""
    cfg = _spool_cfg(tmp_path, exchange_spool_orphan_age_s=3600)
    root = cfg.exchange_spool_path
    # plant a stale orphan a crashed coordinator would have left
    orphan = os.path.join(root, "deadbeef00000000", "deadbeef.0.0", "0")
    os.makedirs(orphan)
    with open(os.path.join(orphan, "00000000.page"), "wb") as f:
        f.write(b"stale")
    old = time.time() - 7200
    os.utime(os.path.join(root, "deadbeef00000000"), (old, old))
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        assert not os.path.exists(
            os.path.join(root, "deadbeef00000000")), "orphan not swept"
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        qid = list(dqr.coordinator.queries)[0]
        # GC runs in the query thread's finally, just after the client
        # unblocks — poll briefly
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                os.path.exists(os.path.join(root, qid)):
            time.sleep(0.05)
        assert not os.path.exists(os.path.join(root, qid)), \
            "query spool dir leaked"


@pytest.mark.slow
def test_q72_kill_every_stage_zero_producer_reruns(tmp_path):
    """The acceptance sweep: kill every stage of TPC-DS Q72 in turn
    (SF0.003, 2-worker DQR, spooling on) — each run recovers with ZERO
    producer re-runs and exact rows."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from tools.chaos_run import run_spool_sweep

    report = run_spool_sweep(
        scale=0.003, spooling=True,
        spool_path=str(tmp_path / "sweep-spool"), quiet=True)
    assert report["ok"], report
    assert report["total_producer_reruns"] == 0
    assert all(s["recovery_rounds"] >= 1 for s in report["stages"])


@pytest.mark.slow
def test_q72_stage_kill_spooling_off_cascades(tmp_path):
    """The contrast pin: the same kill on Q72's big mid-plan join
    fragment with ``exchange_spooling_enabled=false`` restores PR 5
    cascading retry exactly — the producer subtree re-runs."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    from tools.chaos_run import run_spool_sweep

    # fragment 10 consumes Q72's nine leaf fragments: losing it must
    # re-execute that whole subtree when there is no spool
    report = run_spool_sweep(
        scale=0.003, spooling=False, fragments=[10],
        spool_path=str(tmp_path / "sweep-nospool"), quiet=True)
    assert all(s["ok"] for s in report["stages"]), report
    assert report["total_producer_reruns"] >= 1
    assert report["stages"][0]["stage_retry_rounds"] >= 1


def test_spooling_off_writes_nothing(tmp_path):
    """The off switch really restores the PR 5 data plane: no spool
    directory is ever created."""
    cfg = dataclasses.replace(
        DEFAULT, exchange_spooling_enabled=False,
        exchange_spool_path=str(tmp_path / "spool-off"))
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        assert dqr.execute("select count(*) from nation").rows == [(25,)]
        q = list(dqr.coordinator.queries.values())[0]
        assert q.producer_reruns_total == 0
    assert not os.path.exists(str(tmp_path / "spool-off"))


# -- object-store tier (ObjectStoreSpoolStore) ------------------------------

def _object_store(tmp_path, **over):
    from presto_tpu.server.spool import (
        LocalObjectApi, ObjectStoreSpoolStore,
    )

    fb = FileSystemSpoolStore(str(tmp_path / "os"))
    return ObjectStoreSpoolStore(
        LocalObjectApi(str(tmp_path / "os" / "objects")), fallback=fb,
        **over)


def test_object_store_roundtrip_segments_byte_exact(tmp_path):
    """The object tier honors the exact SpoolStore contract while
    compacting pages into multi-page segment objects: fewer objects
    than pages, every re-read byte-exact, resume at any token."""
    store = _object_store(tmp_path, segment_max_bytes=64)
    tid = "q9.1.0"
    pages = [bytes([i]) * (20 + i) for i in range(12)]
    for t, p in enumerate(pages):
        store.write_page(tid, 0, t, p)
    # pending (not-yet-flushed) pages are servable immediately
    got, nxt, complete = store.get_pages(tid, 0, 0, max_bytes=1 << 20)
    assert got == pages and not complete
    assert not store.is_complete(tid, 1)
    store.set_complete(tid, 0, len(pages))
    assert store.is_complete(tid, 1)
    got, nxt, complete = store.get_pages(tid, 0, 0, max_bytes=1 << 20)
    assert got == pages and complete and nxt == 12
    # mid-stream resume (the late re-fetch / repoint shape)
    got, nxt, complete = store.get_pages(tid, 0, 7)
    assert got == pages[7:] and complete
    # compaction really happened: multiple pages per object
    segs = store.api.list(f"q9/{tid}/0/seg-")
    assert 0 < len(segs) < len(pages), segs
    assert store.stats["segments_written"] == len(segs)
    store.close()


def test_object_store_read_through_and_gc(tmp_path):
    """Tokens the object tier does not hold read through to the FS
    tier (mixed history), and delete_query clears both tiers plus the
    pending buffers."""
    store = _object_store(tmp_path)
    # an FS-tier node wrote this stream (pre-switch history)
    fs = store.fallback
    fs.write_page("qf.0.0", 0, 0, b"fs-page-0")
    fs.write_page("qf.0.0", 0, 1, b"fs-page-1")
    fs.set_complete("qf.0.0", 0, 2)
    got, nxt, complete = store.get_pages("qf.0.0", 0, 0)
    assert got == [b"fs-page-0", b"fs-page-1"] and complete
    assert store.is_complete("qf.0.0", 1)
    # GC drops both tiers
    store.write_page("qf.0.0", 0, 2, b"obj-page")
    assert store.delete_query("qf")
    assert store.get_pages("qf.0.0", 0, 0) == ([], 0, False)
    assert not store.is_complete("qf.0.0", 1)
    store.close()


def test_object_store_orphan_sweep_skips_bucket(tmp_path):
    """The FS tier's orphan sweep must never mistake the nested object
    bucket for a stale query directory, while the object tier's own
    sweep age-guards per query prefix."""
    store = _object_store(tmp_path)
    store.write_page("old.0.0", 0, 0, b"x")
    store.flush()
    store.fallback.write_page("oldfs.0.0", 0, 0, b"y")
    old_obj = os.path.join(store.api.root, "old")
    old_fs = os.path.join(store.fallback.root, "oldfs")
    past = time.time() - 7200
    os.utime(old_obj, (past, past))
    os.utime(old_fs, (past, past))
    assert store.sweep_orphans(max_age_s=3600) == 2
    assert not os.path.exists(old_obj)
    assert not os.path.exists(old_fs)
    # the bucket itself survived even though it is now quiet
    assert os.path.isdir(store.api.root)
    store.close()


def _object_cfg(tmp_path, **over):
    return _spool_cfg(tmp_path, exchange_spool_tier="object", **over)


def test_object_tier_buffer_eviction_reserves_byte_exact(tmp_path):
    """Output-buffer eviction against the OBJECT tier: evicted pages —
    including ones still pending in the store's in-memory batch, not
    yet flushed as segments — re-serve byte-exact on a late re-fetch,
    before AND after the async flush."""
    from presto_tpu.server.buffers import OutputBufferManager

    # a huge flush interval pins pages in the pending buffer until the
    # explicit flush below — the pre-flush re-serve path
    store = _object_store(tmp_path, segment_max_bytes=1 << 20,
                          flush_interval_s=60.0)
    pages = [bytes([i]) * 100 for i in range(10)]
    mgr = OutputBufferManager(1, max_buffer_bytes=250, spool=store,
                              task_id="q8.0.0")
    for p in pages:
        mgr.enqueue(0, p)          # never blocks: eviction makes room
    assert mgr.pages_evicted >= 8
    # nothing flushed yet (60s interval, below the size trigger): the
    # evicted prefix re-serves from the store's PENDING buffer
    assert store.stats["segments_written"] == 0
    pre, _nxt, _c = mgr.get_pages(0, 0, max_bytes=1 << 20)
    assert pre and pre == pages[:len(pre)]
    mgr.set_no_more_pages()        # flushes synchronously + COMPLETE
    assert mgr.spooled_complete()
    got, nxt, complete = mgr.get_pages(0, 0, max_bytes=1 << 20)
    while not complete:
        more, nxt, complete = mgr.get_pages(0, nxt, max_bytes=1 << 20)
        got.extend(more)
    assert got == pages and nxt == 10
    # and again after everything is durable as segments
    store.flush()
    got2, nxt2, complete2 = store.get_pages("q8.0.0", 0, 0,
                                            max_bytes=1 << 20)
    assert got2 == pages and complete2
    store.close()


def test_object_tier_cluster_exact_rows_and_segments(tmp_path):
    """A real 2-worker cluster on the object tier: exact rows end to
    end, pages written through as batched segment objects on every
    node."""
    cfg = _object_cfg(tmp_path)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        rows = dqr.execute(
            "select l_returnflag, count(*) as c from lineitem "
            "group by l_returnflag order by l_returnflag").rows
        assert [r[1] for r in rows] == [14613, 30502, 14670]
        from presto_tpu.server.spool import ObjectStoreSpoolStore

        assert isinstance(co.spool, ObjectStoreSpoolStore)
        assert all(isinstance(w.spool, ObjectStoreSpoolStore)
                   for w in dqr.workers)
        spooled = sum(w.spool.stats["pages_written"]
                      for w in dqr.workers)
        assert spooled > 0


def test_object_tier_kill_after_finish_zero_reruns(tmp_path):
    """The PR 7 headline holds on the object tier: a worker lost after
    its tasks finished costs zero producer re-runs — consumers repoint
    at (object-store) spooled output whose completeness the
    coordinator verified through segments + COMPLETE objects."""
    cfg = _object_cfg(tmp_path)
    co_inj, hold = _drain_hold_injector()
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=co_inj,
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select count(*) from lineitem").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        qid = _wait_all_spooled(co, dqr)
        q = co.queries[qid]
        dqr.kill_worker(1)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not co.nodes.dead_uris():
            time.sleep(0.02)
        hold.release()
        t.join(timeout=120)
        assert not t.is_alive()
        assert "err" not in res, res
        assert res["rows"] == [(59785,)]   # exact SF0.01 count
        assert q.producer_reruns_total == 0


def test_object_tier_spool_read_error_retried(tmp_path):
    """faults.py spool policies hit the object tier's read path the
    same way they hit the FS tier's: transient read errors retry on
    the error-budget discipline, rows stay exact."""
    cfg = _object_cfg(tmp_path)
    co_inj, hold = _drain_hold_injector()
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=co_inj,
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2) as dqr:
        co = dqr.coordinator
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select count(*) from lineitem").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        qid = _wait_all_spooled(co, dqr)
        q = co.queries[qid]
        victim_idx, _uri = _root_worker(q, dqr)
        dqr.kill_worker(victim_idx)
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and not q._spool_moves:
            time.sleep(0.02)
        assert q._spool_moves
        # NOW fault the coordinator's spool reads: the root drain must
        # retry them against the OBJECT tier's segment path exactly as
        # it retries the FS tier's page files
        rule = co_inj.add_spool_rule(r".", policy="spool-read-error",
                                     times=2)
        hold.release()
        t.join(timeout=60)
        assert not t.is_alive()
        assert "err" not in res, res
        assert res["rows"] == [(59785,)]   # exact SF0.01 count
        assert q.producer_reruns_total == 0
        assert rule.remaining == 0      # both faults really fired
