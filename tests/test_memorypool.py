"""Worker memory pool tests (server/memorypool.py + the reservation
tree's pool charging in exec/context.py).

Reference analogues: MemoryPool / LocalMemoryContext blocking semantics
(presto-memory-context, presto-main/.../memory/MemoryPool.java): a
reservation that does not fit BLOCKS the driver; frees (from any query)
unblock it; a killed query's blocked drivers wake with an abort; the
pool's pressure signal drives revoke-first spilling; and with the knob
off (``worker_memory_pool_bytes = 0``) the pool accounts but NEVER
blocks — the exact pre-pool behavior."""

import dataclasses
import threading
import time

import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.exec.context import (
    MemoryContext, OperatorContext, QueryContext, TaskContext,
)
from presto_tpu.server.memorypool import (
    MemoryPool, MemoryPoolExhausted, QueryAborted,
)


def _spin_until(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


# ---------------------------------------------------------------------------
# pool primitive
# ---------------------------------------------------------------------------

def test_unlimited_pool_accounts_but_never_blocks():
    pool = MemoryPool(0, blocked_wait_s=0.05)
    assert not pool.limited
    pool.reserve("q1", 1 << 40)            # absurd: must not block/raise
    pool.reserve("q2", 123)
    info = pool.info()
    assert info["maxBytes"] == 0
    assert info["reservedBytes"] == (1 << 40) + 123
    assert info["queries"] == {"q1": 1 << 40, "q2": 123}
    assert info["blockedDrivers"] == 0
    pool.free("q1", 1 << 40)
    pool.free("q2", 123)
    assert pool.info()["reservedBytes"] == 0
    assert pool.info()["queries"] == {}
    assert not pool.needs_revoke()         # pressure signal off too


def test_full_pool_blocks_until_free():
    pool = MemoryPool(1000, blocked_wait_s=10.0)
    pool.reserve("holder", 900)
    got = []

    def blocked():
        pool.reserve("waiter", 200)        # 1100 > 1000: blocks
        got.append(True)

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    assert _spin_until(lambda: pool.info()["blockedDrivers"] == 1)
    assert not got
    assert pool.info()["blockedAgeS"] >= 0.0
    pool.free("holder", 500)               # now 400 + 200 fits
    t.join(timeout=5)
    assert got == [True]
    assert pool.info()["blockedDrivers"] == 0
    assert pool.info()["queries"] == {"holder": 400, "waiter": 200}


def test_blocked_wait_backstop_raises_exhausted():
    pool = MemoryPool(100, blocked_wait_s=0.1)
    pool.reserve("holder", 100)
    t0 = time.monotonic()
    with pytest.raises(MemoryPoolExhausted):
        pool.reserve("waiter", 50)
    assert time.monotonic() - t0 >= 0.09
    # the failed charge left nothing behind
    assert pool.info()["queries"] == {"holder": 100}


def test_abort_wakes_blocked_driver_promptly():
    pool = MemoryPool(100, blocked_wait_s=30.0)
    pool.reserve("holder", 100)
    err = []

    def blocked():
        try:
            pool.reserve("victim", 50)
        except QueryAborted as e:
            err.append(e)

    t = threading.Thread(target=blocked, daemon=True)
    t.start()
    assert _spin_until(lambda: pool.info()["blockedDrivers"] == 1)
    pool.abort_query("victim")
    t.join(timeout=5)
    assert len(err) == 1                   # promptly, not the 30s backstop
    assert pool.is_aborted("victim")
    pool.clear_abort("victim")
    assert not pool.is_aborted("victim")


def test_full_release_drops_abort_flag():
    pool = MemoryPool(1000)
    pool.reserve("q", 10)
    pool.abort_query("q")
    assert pool.is_aborted("q")
    pool.free("q", 10)                     # fully released -> clean slate
    assert not pool.is_aborted("q")


def test_needs_revoke_pressure_signal():
    pool = MemoryPool(1000, blocked_wait_s=10.0)
    assert not pool.needs_revoke()
    pool.reserve("q", 400)
    assert not pool.needs_revoke()         # under half
    pool.reserve("q", 100)
    assert pool.needs_revoke()             # at half: revoke before blocking
    pool.free("q", 400)
    assert not pool.needs_revoke()
    # a blocked driver is pressure regardless of fill level
    pool2 = MemoryPool(100, blocked_wait_s=5.0)
    pool2.reserve("holder", 100)
    t = threading.Thread(target=lambda: pool2.reserve("w", 50),
                         daemon=True)
    t.start()
    assert _spin_until(pool2.needs_revoke)
    pool2.free("holder", 100)
    t.join(timeout=5)


def test_peak_tracks_high_water_mark():
    pool = MemoryPool(0)
    pool.reserve("a", 700)
    pool.free("a", 600)
    pool.reserve("b", 100)
    assert pool.info()["peakBytes"] == 700
    assert pool.info()["reservedBytes"] == 200


# ---------------------------------------------------------------------------
# reservation tree -> pool charging (exec/context.py)
# ---------------------------------------------------------------------------

def test_reservation_tree_charges_root_deltas_into_pool():
    pool = MemoryPool(0)
    q = QueryContext(pool=pool, pool_query_id="q7")
    task = TaskContext(q, "q7.0.0")
    op = OperatorContext(task, "sort")
    op.memory.reserve(500)
    assert pool.info()["queries"] == {"q7": 500}
    op.memory.set_bytes(200)               # shrink frees the pool after
    assert pool.info()["queries"] == {"q7": 200}
    op.memory.free()
    assert pool.info()["reservedBytes"] == 0
    # two tasks of one query fold into one pool entry
    op2 = OperatorContext(TaskContext(q, "q7.1.0"), "join")
    op.memory.reserve(100)
    op2.memory.reserve(50)
    assert pool.info()["queries"] == {"q7": 150}


def test_failed_tree_charge_leaves_tree_and_pool_untouched():
    """Charge-before-apply: when the pool rejects (abort mid-block),
    the reservation tree must not have grown."""
    pool = MemoryPool(100, blocked_wait_s=5.0)
    pool.reserve("other", 100)
    q = QueryContext(pool=pool, pool_query_id="qx")
    op = OperatorContext(TaskContext(q, "qx.0.0"), "agg")
    pool.abort_query("qx")
    with pytest.raises(QueryAborted):
        op.memory.reserve(50)
    assert op.memory.reserved == 0
    assert q.memory.reserved == 0
    assert pool.info()["queries"] == {"other": 100}


def test_release_pool_backstop_returns_remaining_charge():
    pool = MemoryPool(0)
    q = QueryContext(pool=pool, pool_query_id="q9")
    op = OperatorContext(TaskContext(q, "q9.0.0"), "scan")
    op.memory.reserve(300)
    q.release_pool()
    assert pool.info()["reservedBytes"] == 0
    # detached: further tree traffic never touches the pool
    op.memory.reserve(100)
    assert pool.info()["reservedBytes"] == 0


def test_pool_free_capped_by_charged_bytes():
    """A tree attached to the pool mid-life only frees what IT charged
    (never another query's bytes)."""
    pool = MemoryPool(0)
    pool.reserve("q5", 1000)               # charged outside the tree
    q = QueryContext(pool=pool, pool_query_id="q5")
    ctx = MemoryContext(q.memory, "op")
    ctx.reserve(100)
    ctx.free()
    ctx.reserve(40)
    ctx.set_bytes(0)
    assert pool.info()["queries"] == {"q5": 1000}


# ---------------------------------------------------------------------------
# revoke-first spill decision (OperatorContext.should_spill)
# ---------------------------------------------------------------------------

def _spill_cfg(**kw):
    return dataclasses.replace(DEFAULT, spill_threshold_bytes=1000, **kw)


def test_should_spill_threshold_path():
    q = QueryContext(config=_spill_cfg())
    op = OperatorContext(TaskContext(q), "join-build")
    assert not op.should_spill(999)
    assert op.should_spill(1001)


def test_should_spill_on_pool_pressure_below_threshold():
    pool = MemoryPool(1000)
    pool.reserve("hog", 600)               # past half: needs_revoke
    q = QueryContext(config=_spill_cfg(), pool=pool, pool_query_id="s1")
    op = OperatorContext(TaskContext(q), "sort")
    assert op.should_spill(10)             # far below threshold: revoke
    pool.free("hog", 600)
    assert not op.should_spill(10)


def test_should_spill_disabled_ignores_pressure():
    pool = MemoryPool(1000)
    pool.reserve("hog", 999)
    q = QueryContext(config=_spill_cfg(spill_enabled=False),
                     pool=pool, pool_query_id="s2")
    op = OperatorContext(TaskContext(q), "sort")
    assert not op.should_spill(1 << 30)


# ---------------------------------------------------------------------------
# knobs-off identity
# ---------------------------------------------------------------------------

def test_knobs_off_defaults_pinned():
    """The overload plane is OFF by default: unlimited pool, no killer
    pressure, thread-per-query dispatch, no shedding — existing
    deployments see exactly the old behavior."""
    assert DEFAULT.worker_memory_pool_bytes == 0
    assert DEFAULT.query_max_total_memory_bytes == 0
    assert DEFAULT.dispatcher_pool_size == 0
    assert DEFAULT.dispatcher_max_queued == 0
    assert not MemoryPool(DEFAULT.worker_memory_pool_bytes).limited
    # a query context built with no pool (the localrunner/default path)
    # has zero pool coupling
    q = QueryContext()
    assert q.memory.pool is None
    op = OperatorContext(TaskContext(q), "agg")
    op.memory.reserve(1 << 20)             # plain tree accounting only
    assert q.memory.reserved == 1 << 20
