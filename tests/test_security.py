"""Authentication / proxy / session-property-manager tests
(presto-password-authenticators, InternalAuthenticationManager,
presto-proxy, presto-session-property-managers roles)."""

import json
import urllib.error
import urllib.request

import pytest

from presto_tpu.server.security import (
    InternalAuthenticator, PasswordAuthenticator,
)


def test_password_file_roundtrip(tmp_path):
    path = str(tmp_path / "password.db")
    auth = PasswordAuthenticator(path)
    auth.set_password("alice", "open sesame")
    auth.set_password("bob", "hunter2")
    # reload from disk
    auth2 = PasswordAuthenticator(path)
    assert auth2.authenticate("alice", "open sesame")
    assert not auth2.authenticate("alice", "wrong")
    assert not auth2.authenticate("carol", "open sesame")
    # no plaintext in the file
    assert "hunter2" not in open(path).read()


def test_basic_header_parsing():
    auth = PasswordAuthenticator()
    auth.set_password("u", "p")
    import base64

    good = "Basic " + base64.b64encode(b"u:p").decode()
    bad = "Basic " + base64.b64encode(b"u:x").decode()
    assert auth.authenticate_basic(good) == "u"
    assert auth.authenticate_basic(bad) is None
    assert auth.authenticate_basic(None) is None
    assert auth.authenticate_basic("Bearer zzz") is None


def test_internal_authenticator():
    a = InternalAuthenticator("secret1")
    b = InternalAuthenticator("secret1")
    c = InternalAuthenticator("other")
    tok = a.header()[InternalAuthenticator.HEADER]
    assert b.verify(tok)
    assert not c.verify(tok)
    assert not a.verify(None)
    assert "secret1" not in tok


def _post(url, body, headers=None):
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_coordinator_password_auth_and_proxy(tmp_path):
    import base64

    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.proxy import ProxyServer
    from presto_tpu.server.worker import WorkerServer

    auth = PasswordAuthenticator()
    auth.set_password("alice", "pw")

    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.01))
    co = CoordinatorServer(reg, "tpch", authenticator=auth,
                           internal_secret="cluster-secret")
    w = WorkerServer(ConnectorRegistry(), node_id="w0",
                     internal_secret="cluster-secret")
    try:
        # unauthenticated statement -> 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{co.uri}/v1/statement", b"SELECT 1")
        assert ei.value.code == 401
        # authenticated through the coordinator directly
        basic = "Basic " + base64.b64encode(b"alice:pw").decode()
        status, payload = _post(f"{co.uri}/v1/statement", b"SHOW CATALOGS",
                                {"Authorization": basic})
        assert status == 200 and "nextUri" in payload

        # worker rejects unauthenticated task create, status and results
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{w.uri}/v1/task/t1", b"{}",
                  {"Content-Type": "application/json"})
        assert ei.value.code == 401
        for path in ("/v1/task", "/v1/task/t1/results/0/0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{w.uri}{path}", timeout=5)
            assert ei.value.code == 401
        # coordinator observability endpoints require auth too
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{co.uri}/v1/query", timeout=5)
        assert ei.value.code == 401
        # unauthenticated announcement rejected (token-leak vector)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{co.uri}/v1/announcement",
                  json.dumps({"nodeId": "evil",
                              "uri": "http://127.0.0.1:1"}).encode())
        assert ei.value.code == 401

        # the proxy authenticates and forwards; nextUri points at the
        # proxy, and the full protocol works through it
        proxy = ProxyServer(co.uri, auth,
                            internal_secret="cluster-secret")
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"{proxy.uri}/v1/statement", b"SELECT 1")
            assert ei.value.code == 401

            class AuthedClient(StatementClient):
                pass

            # monkey-free: drive protocol manually with auth header
            req = urllib.request.Request(
                f"{proxy.uri}/v1/statement", data=b"SHOW CATALOGS",
                method="POST", headers={"Authorization": basic})
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            assert payload["nextUri"].startswith(proxy.uri)
            import time

            rows = None
            for _ in range(100):
                req = urllib.request.Request(
                    payload["nextUri"],
                    headers={"Authorization": basic})
                with urllib.request.urlopen(req, timeout=30) as resp:
                    payload2 = json.loads(resp.read())
                if "data" in payload2 or "columns" in payload2:
                    rows = payload2.get("data", [])
                    break
                payload = payload2 if payload2.get("nextUri") else payload
                time.sleep(0.05)
            assert rows == [["tpch"]]
        finally:
            proxy.close()
    finally:
        w.close()
        co.close()


def test_session_property_manager():
    from presto_tpu.session import Session, SessionPropertyManager

    mgr = SessionPropertyManager([
        {"user": "*", "properties": {"task_concurrency": 2}},
        {"user": "etl_*", "properties": {"spill_enabled": "true",
                                         "task_concurrency": 8}},
    ])
    s = Session(user="etl_nightly")
    mgr.apply(s)
    assert s.properties["task_concurrency"] == 8
    assert s.properties["spill_enabled"] is True
    s2 = Session(user="adhoc")
    mgr.apply(s2)
    assert s2.properties["task_concurrency"] == 2
    assert "spill_enabled" not in s2.properties
    # explicit SET SESSION wins over defaults
    s3 = Session(user="etl_x")
    s3.set_property("task_concurrency", "1")
    mgr.apply(s3)
    assert s3.properties["task_concurrency"] == 1


def test_runner_with_property_manager():
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.session import Session, SessionPropertyManager

    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.01))
    mgr = SessionPropertyManager(
        [{"user": "*", "properties": {"scan_batch_rows": 1234}}])
    r = LocalQueryRunner(reg, "tpch", session=Session(user="u"),
                        session_property_manager=mgr)
    got = dict((n, v) for n, v, _ in r.session.show_properties())
    assert got["scan_batch_rows"] == "1234"


def test_plan_text_in_query_detail():
    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        dqr.execute("SELECT l_returnflag, count(*) FROM lineitem "
                    "GROUP BY l_returnflag")
        co = dqr.coordinator
        qid = next(iter(co.queries))
        with urllib.request.urlopen(f"{co.uri}/v1/query/{qid}",
                                    timeout=10) as resp:
            detail = json.loads(resp.read())
        assert "Fragment 0" in detail["plan"]
        assert "Aggregation" in detail["plan"]


def test_secured_dqr_end_to_end():
    """A whole secured cluster through DistributedQueryRunner: the
    announce/task/exchange paths all carry the cluster token."""
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.dqr import DistributedQueryRunner

    def factory():
        reg = ConnectorRegistry()
        reg.register("tpch", TpchConnector(scale=0.01))
        return reg

    with DistributedQueryRunner(factory, "tpch", n_workers=2,
                                internal_secret="dqr-secret") as dqr:
        got = dqr.execute(
            "SELECT l_returnflag, count(*) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag").rows
        assert [r[0] for r in got] == ["A", "N", "R"]
        # a tokenless fetch against a worker's results is still rejected
        import urllib.error
        import urllib.request

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{dqr.workers[0].uri}/v1/task", timeout=5)
        assert ei.value.code == 401


# ---------------------------------------------------------------------------
# JWT / certificate tiers (JsonWebTokenAuthenticator.java,
# CertificateAuthenticator.java roles; VERDICT r3 #10)
# ---------------------------------------------------------------------------

def test_jwt_roundtrip_and_rejection():
    from presto_tpu.server.security import JwtAuthenticator, jwt_decode

    a = JwtAuthenticator("k1", issuer="corp", audience="presto")
    tok = a.create_token("alice")
    assert a.authenticate_header({"Authorization": f"Bearer {tok}"}) \
        == "alice"
    # wrong key
    b = JwtAuthenticator("other", issuer="corp", audience="presto")
    assert b.authenticate_header({"Authorization": f"Bearer {tok}"}) is None
    # wrong issuer / audience
    c = JwtAuthenticator("k1", issuer="else", audience="presto")
    assert c.authenticate_header({"Authorization": f"Bearer {tok}"}) is None
    # tampered payload
    h, p, s = tok.split(".")
    assert a.authenticate_header(
        {"Authorization": f"Bearer {h}.{p[:-2]}AA.{s}"}) is None
    # alg header is not trusted (alg=none style downgrade)
    import base64 as b64
    forged_h = b64.urlsafe_b64encode(
        b'{"alg":"none","typ":"JWT"}').rstrip(b"=").decode()
    assert jwt_decode(f"{forged_h}.{p}.{s}", "k1") is None


def test_jwt_expiry():
    from presto_tpu.server.security import JwtAuthenticator, jwt_decode

    a = JwtAuthenticator("k1")
    tok = a.create_token("bob", ttl_s=-1)          # already expired
    assert a.authenticate_header({"Authorization": f"Bearer {tok}"}) is None
    tok2 = a.create_token("bob", ttl_s=60)
    import time
    assert jwt_decode(tok2, "k1", now=time.time() + 120) is None


def test_internal_tokens_expire_and_rotate():
    from presto_tpu.server.security import InternalAuthenticator

    a = InternalAuthenticator("s", ttl_s=0.05)
    tok = a.header()[InternalAuthenticator.HEADER]
    assert a.verify(tok)
    import time
    time.sleep(0.08)
    assert not a.verify(tok)                # captured token stops working
    tok2 = a.header()[InternalAuthenticator.HEADER]
    assert tok2 != tok and a.verify(tok2)   # fresh token auto-minted


def test_certificate_authenticator():
    from presto_tpu.server.security import CertificateAuthenticator

    cert = {"subject": ((("commonName", "svc-reporting"),),),
            "issuer": ((("commonName", "corp-ca"),),)}
    assert CertificateAuthenticator().authenticate_cert(cert) \
        == "svc-reporting"
    assert CertificateAuthenticator("corp-ca").authenticate_cert(cert) \
        == "svc-reporting"
    assert CertificateAuthenticator("other-ca").authenticate_cert(cert) \
        is None
    assert CertificateAuthenticator().authenticate_cert(None) is None


def test_jwt_bearer_against_live_coordinator():
    """Secured cluster end-to-end: Bearer JWT accepted, expired/garbage
    rejected with 401, Basic password still works through the stack."""
    import urllib.error
    import urllib.request

    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.security import (
        AuthenticatorStack, JwtAuthenticator, PasswordAuthenticator,
    )
    from presto_tpu.server.worker import WorkerServer

    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.001))
    pw = PasswordAuthenticator()
    pw.set_password("carol", "pw123")
    jwt_auth = JwtAuthenticator("jwt-secret")
    co = CoordinatorServer(reg, "tpch",
                           authenticator=AuthenticatorStack(jwt_auth, pw),
                           internal_secret="cs")

    def reg2():
        r = ConnectorRegistry()
        r.register("tpch", TpchConnector(scale=0.001))
        return r

    # (co.uri was accidentally passed as the ``config`` positional here;
    # harmless while config attributes were only read lazily, an
    # AttributeError now that WorkerServer builds its HTTP client from
    # config at construction)
    w = WorkerServer(reg2(), internal_secret="cs")
    try:
        def post(headers):
            req = urllib.request.Request(
                f"{co.uri}/v1/statement",
                data=b"SELECT count(*) FROM tpch.region",
                headers={"X-Presto-User": "x", **headers})
            return urllib.request.urlopen(req, timeout=30).status

        tok = jwt_auth.create_token("carol", ttl_s=60)
        assert post({"Authorization": f"Bearer {tok}"}) == 200
        expired = jwt_auth.create_token("carol", ttl_s=-1)
        for bad in ({"Authorization": f"Bearer {expired}"},
                    {"Authorization": "Bearer junk"},
                    {}):
            try:
                post(bad)
                raise AssertionError(f"expected 401 for {bad}")
            except urllib.error.HTTPError as e:
                assert e.code == 401
        # password Basic still works through the stack
        import base64
        basic = "Basic " + base64.b64encode(b"carol:pw123").decode()
        assert post({"Authorization": basic}) == 200
    finally:
        w.close()
        co.close()
