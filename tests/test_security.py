"""Authentication / proxy / session-property-manager tests
(presto-password-authenticators, InternalAuthenticationManager,
presto-proxy, presto-session-property-managers roles)."""

import json
import urllib.error
import urllib.request

import pytest

from presto_tpu.server.security import (
    InternalAuthenticator, PasswordAuthenticator,
)


def test_password_file_roundtrip(tmp_path):
    path = str(tmp_path / "password.db")
    auth = PasswordAuthenticator(path)
    auth.set_password("alice", "open sesame")
    auth.set_password("bob", "hunter2")
    # reload from disk
    auth2 = PasswordAuthenticator(path)
    assert auth2.authenticate("alice", "open sesame")
    assert not auth2.authenticate("alice", "wrong")
    assert not auth2.authenticate("carol", "open sesame")
    # no plaintext in the file
    assert "hunter2" not in open(path).read()


def test_basic_header_parsing():
    auth = PasswordAuthenticator()
    auth.set_password("u", "p")
    import base64

    good = "Basic " + base64.b64encode(b"u:p").decode()
    bad = "Basic " + base64.b64encode(b"u:x").decode()
    assert auth.authenticate_basic(good) == "u"
    assert auth.authenticate_basic(bad) is None
    assert auth.authenticate_basic(None) is None
    assert auth.authenticate_basic("Bearer zzz") is None


def test_internal_authenticator():
    a = InternalAuthenticator("secret1")
    b = InternalAuthenticator("secret1")
    c = InternalAuthenticator("other")
    tok = a.header()[InternalAuthenticator.HEADER]
    assert b.verify(tok)
    assert not c.verify(tok)
    assert not a.verify(None)
    assert "secret1" not in tok


def _post(url, body, headers=None):
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_coordinator_password_auth_and_proxy(tmp_path):
    import base64

    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.proxy import ProxyServer
    from presto_tpu.server.worker import WorkerServer

    auth = PasswordAuthenticator()
    auth.set_password("alice", "pw")

    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.01))
    co = CoordinatorServer(reg, "tpch", authenticator=auth,
                           internal_secret="cluster-secret")
    w = WorkerServer(ConnectorRegistry(), node_id="w0",
                     internal_secret="cluster-secret")
    try:
        # unauthenticated statement -> 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{co.uri}/v1/statement", b"SELECT 1")
        assert ei.value.code == 401
        # authenticated through the coordinator directly
        basic = "Basic " + base64.b64encode(b"alice:pw").decode()
        status, payload = _post(f"{co.uri}/v1/statement", b"SHOW CATALOGS",
                                {"Authorization": basic})
        assert status == 200 and "nextUri" in payload

        # worker rejects unauthenticated task create, status and results
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{w.uri}/v1/task/t1", b"{}",
                  {"Content-Type": "application/json"})
        assert ei.value.code == 401
        for path in ("/v1/task", "/v1/task/t1/results/0/0"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{w.uri}{path}", timeout=5)
            assert ei.value.code == 401
        # coordinator observability endpoints require auth too
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{co.uri}/v1/query", timeout=5)
        assert ei.value.code == 401
        # unauthenticated announcement rejected (token-leak vector)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{co.uri}/v1/announcement",
                  json.dumps({"nodeId": "evil",
                              "uri": "http://127.0.0.1:1"}).encode())
        assert ei.value.code == 401

        # the proxy authenticates and forwards; nextUri points at the
        # proxy, and the full protocol works through it
        proxy = ProxyServer(co.uri, auth,
                            internal_secret="cluster-secret")
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"{proxy.uri}/v1/statement", b"SELECT 1")
            assert ei.value.code == 401

            class AuthedClient(StatementClient):
                pass

            # monkey-free: drive protocol manually with auth header
            req = urllib.request.Request(
                f"{proxy.uri}/v1/statement", data=b"SHOW CATALOGS",
                method="POST", headers={"Authorization": basic})
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
            assert payload["nextUri"].startswith(proxy.uri)
            import time

            rows = None
            for _ in range(100):
                req = urllib.request.Request(
                    payload["nextUri"],
                    headers={"Authorization": basic})
                with urllib.request.urlopen(req, timeout=30) as resp:
                    payload2 = json.loads(resp.read())
                if "data" in payload2 or "columns" in payload2:
                    rows = payload2.get("data", [])
                    break
                payload = payload2 if payload2.get("nextUri") else payload
                time.sleep(0.05)
            assert rows == [["tpch"]]
        finally:
            proxy.close()
    finally:
        w.close()
        co.close()


def test_session_property_manager():
    from presto_tpu.session import Session, SessionPropertyManager

    mgr = SessionPropertyManager([
        {"user": "*", "properties": {"task_concurrency": 2}},
        {"user": "etl_*", "properties": {"spill_enabled": "true",
                                         "task_concurrency": 8}},
    ])
    s = Session(user="etl_nightly")
    mgr.apply(s)
    assert s.properties["task_concurrency"] == 8
    assert s.properties["spill_enabled"] is True
    s2 = Session(user="adhoc")
    mgr.apply(s2)
    assert s2.properties["task_concurrency"] == 2
    assert "spill_enabled" not in s2.properties
    # explicit SET SESSION wins over defaults
    s3 = Session(user="etl_x")
    s3.set_property("task_concurrency", "1")
    mgr.apply(s3)
    assert s3.properties["task_concurrency"] == 1


def test_runner_with_property_manager():
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.session import Session, SessionPropertyManager

    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.01))
    mgr = SessionPropertyManager(
        [{"user": "*", "properties": {"scan_batch_rows": 1234}}])
    r = LocalQueryRunner(reg, "tpch", session=Session(user="u"),
                        session_property_manager=mgr)
    got = dict((n, v) for n, v, _ in r.session.show_properties())
    assert got["scan_batch_rows"] == "1234"


def test_plan_text_in_query_detail():
    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        dqr.execute("SELECT l_returnflag, count(*) FROM lineitem "
                    "GROUP BY l_returnflag")
        co = dqr.coordinator
        qid = next(iter(co.queries))
        with urllib.request.urlopen(f"{co.uri}/v1/query/{qid}",
                                    timeout=10) as resp:
            detail = json.loads(resp.read())
        assert "Fragment 0" in detail["plan"]
        assert "Aggregation" in detail["plan"]


def test_secured_dqr_end_to_end():
    """A whole secured cluster through DistributedQueryRunner: the
    announce/task/exchange paths all carry the cluster token."""
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.dqr import DistributedQueryRunner

    def factory():
        reg = ConnectorRegistry()
        reg.register("tpch", TpchConnector(scale=0.01))
        return reg

    with DistributedQueryRunner(factory, "tpch", n_workers=2,
                                internal_secret="dqr-secret") as dqr:
        got = dqr.execute(
            "SELECT l_returnflag, count(*) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag").rows
        assert [r[0] for r in got] == ["A", "N", "R"]
        # a tokenless fetch against a worker's results is still rejected
        import urllib.error
        import urllib.request

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{dqr.workers[0].uri}/v1/task", timeout=5)
        assert ei.value.code == 401
