"""Verifier + benchmark-driver tools (presto-verifier /
presto-benchmark-driver roles)."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.verifier import Verifier


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


class TestVerifier:
    def test_match(self, runner):
        other = LocalQueryRunner.tpch(scale=0.001)
        v = Verifier(control=runner, test=other)
        results = v.verify([
            "select count(*) from nation",
            "select r_name, count(*) from region, nation "
            "where r_regionkey = n_regionkey group by r_name",
        ])
        assert all(r.status == "MATCH" for r in results)
        assert "MATCH=2" in Verifier.summarize(results)

    def test_mismatch_detected(self, runner):
        class Wrong:
            def execute(self, sql):
                res = runner.execute(sql)
                import dataclasses as d

                return d.replace(res, rows=res.rows[:-1])

        v = Verifier(control=runner, test=Wrong())
        (r,) = v.verify(["select n_name from nation"])
        assert r.status == "MISMATCH"
        assert "row counts differ" in r.detail

    def test_failure_classified(self, runner):
        class Broken:
            def execute(self, sql):
                raise RuntimeError("boom")

        (r,) = Verifier(runner, Broken()).verify(["select 1"])
        assert r.status == "TEST_FAILED"

    def test_float_tolerance(self, runner):
        class Jittered:
            def execute(self, sql):
                res = runner.execute(sql)
                import dataclasses as d

                rows = [tuple(v + 1e-11 if isinstance(v, float) else v
                              for v in row) for row in res.rows]
                return d.replace(res, rows=rows)

        v = Verifier(runner, Jittered())
        (r,) = v.verify(["select sum(l_quantity) from lineitem"])
        assert r.status == "MATCH"


class TestBenchmarkDriver:
    def test_run_suite(self, runner):
        from presto_tpu.benchmark_driver import load_suite, run_suite

        queries = {k: v for k, v in load_suite("tpch").items()
                   if k in ("q1", "q6")}
        results = run_suite(runner, queries, runs=1, warmup=0)
        assert [r.name for r in results] == ["q1", "q6"]
        assert all(r.median_s > 0 for r in results)
        assert results[0].rows == 4  # Q1 groups

    def test_suite_loading(self):
        from presto_tpu.benchmark_driver import load_suite

        assert len(load_suite("tpch")) == 22
        assert "q72" in load_suite("tpcds")


class TestPlanDiff:
    def test_memo_vs_greedy_diff(self, capsys):
        """tools/plan_diff.py prints both plan shapes with cost
        estimates and reports the memo plan no costlier than greedy."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        plan_diff = importlib.import_module("plan_diff")
        rc = plan_diff.main(["q3", "--scale", "0.001"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "=== memo-on ===" in out
        assert "=== memo-off (greedy) ===" in out
        assert "estimated cost" in out
        assert "WARNING" not in out    # memo never costlier than greedy

    def test_query_name_parsing(self):
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        plan_diff = importlib.import_module("plan_diff")
        catalog, sql = plan_diff.load_query("tpcds/q72")
        assert catalog == "tpcds" and "inventory" in sql
        catalog, _ = plan_diff.load_query("q9")
        assert catalog == "tpch"


class TestFusionReport:
    def test_report_smoke_check_mode(self, capsys):
        """tools/fusion_report.py --execute --check is the CI smoke: it
        plans + runs queries fused and unfused, asserts parity, and
        fails when fusion regresses launch counts to zero coverage."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        fusion_report = importlib.import_module("fusion_report")
        rc = fusion_report.main(
            ["q6", "q3", "--scale", "0.002", "--execute", "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "fused segments" in out
        assert "parity=True" in out
        assert "FusedSegment{" in out


class TestQpsRun:
    def test_check_mode(self, capsys):
        """tools/qps_run.py --check: the serving-tier CI smoke — a tiny
        closed-loop run at 2 concurrency levels against a live 2-worker
        DQR asserting per-client exact-rows parity, nonzero plan-cache
        hits, and zero jit compiles on the second execution of a cached
        plan."""
        import importlib
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        qps_run = importlib.import_module("qps_run")
        rc = qps_run.main(["--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["check"] == {
            "parity": True, "plan_cache_hits": True,
            "zero_second_run_compiles": True,
            "second_run_plan_cached": True}
        levels = payload["report"]["levels"]
        assert [lv["concurrency"] for lv in levels] == [1, 2]
        for lv in levels:
            assert lv["qps"] > 0 and lv["p99_ms"] >= lv["p50_ms"]


class TestQueryProfile:
    def test_live_profile_check_mode(self, capsys, tmp_path):
        """tools/query_profile.py --check: runs a statement on a real
        2-worker DQR and renders the per-stage stats table + task span
        timeline from the coordinator's rollup."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        query_profile = importlib.import_module("query_profile")
        log = str(tmp_path / "query.json")
        rc = query_profile.main(
            ["--scale", "0.002", "--check", "--event-log", log])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "task span timeline" in out
        assert "profile rollup complete" in out
        assert "trace=tt-" in out
        # stage table rendered both fragments with real rows
        assert "xchg f/c/p" in out

        # replay mode renders the log the live run just wrote
        rc = query_profile.main(["--replay", log])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "QueryCreatedEvent" in out
        assert "QueryCompletedEvent" in out
        assert "stage stats for" in out
