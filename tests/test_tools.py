"""Verifier + benchmark-driver tools (presto-verifier /
presto-benchmark-driver roles)."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.verifier import Verifier


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


class TestVerifier:
    def test_match(self, runner):
        other = LocalQueryRunner.tpch(scale=0.001)
        v = Verifier(control=runner, test=other)
        results = v.verify([
            "select count(*) from nation",
            "select r_name, count(*) from region, nation "
            "where r_regionkey = n_regionkey group by r_name",
        ])
        assert all(r.status == "MATCH" for r in results)
        assert "MATCH=2" in Verifier.summarize(results)

    def test_mismatch_detected(self, runner):
        class Wrong:
            def execute(self, sql):
                res = runner.execute(sql)
                import dataclasses as d

                return d.replace(res, rows=res.rows[:-1])

        v = Verifier(control=runner, test=Wrong())
        (r,) = v.verify(["select n_name from nation"])
        assert r.status == "MISMATCH"
        assert "row counts differ" in r.detail

    def test_failure_classified(self, runner):
        class Broken:
            def execute(self, sql):
                raise RuntimeError("boom")

        (r,) = Verifier(runner, Broken()).verify(["select 1"])
        assert r.status == "TEST_FAILED"

    def test_float_tolerance(self, runner):
        class Jittered:
            def execute(self, sql):
                res = runner.execute(sql)
                import dataclasses as d

                rows = [tuple(v + 1e-11 if isinstance(v, float) else v
                              for v in row) for row in res.rows]
                return d.replace(res, rows=rows)

        v = Verifier(runner, Jittered())
        (r,) = v.verify(["select sum(l_quantity) from lineitem"])
        assert r.status == "MATCH"


class TestBenchmarkDriver:
    def test_run_suite(self, runner):
        from presto_tpu.benchmark_driver import load_suite, run_suite

        queries = {k: v for k, v in load_suite("tpch").items()
                   if k in ("q1", "q6")}
        results = run_suite(runner, queries, runs=1, warmup=0)
        assert [r.name for r in results] == ["q1", "q6"]
        assert all(r.median_s > 0 for r in results)
        assert results[0].rows == 4  # Q1 groups

    def test_suite_loading(self):
        from presto_tpu.benchmark_driver import load_suite

        assert len(load_suite("tpch")) == 22
        assert "q72" in load_suite("tpcds")


class TestPlanDiff:
    def test_memo_vs_greedy_diff(self, capsys):
        """tools/plan_diff.py prints both plan shapes with cost
        estimates and reports the memo plan no costlier than greedy."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        plan_diff = importlib.import_module("plan_diff")
        rc = plan_diff.main(["q3", "--scale", "0.001"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "=== memo-on ===" in out
        assert "=== memo-off (greedy) ===" in out
        assert "estimated cost" in out
        assert "WARNING" not in out    # memo never costlier than greedy

    def test_query_name_parsing(self):
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        plan_diff = importlib.import_module("plan_diff")
        catalog, sql = plan_diff.load_query("tpcds/q72")
        assert catalog == "tpcds" and "inventory" in sql
        catalog, _ = plan_diff.load_query("q9")
        assert catalog == "tpch"


class TestFusionReport:
    def test_report_smoke_check_mode(self, capsys):
        """tools/fusion_report.py --execute --check is the CI smoke: it
        plans + runs queries fused and unfused, asserts parity, and
        fails when fusion regresses launch counts to zero coverage."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        fusion_report = importlib.import_module("fusion_report")
        rc = fusion_report.main(
            ["q6", "q3", "--scale", "0.002", "--execute", "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "fused segments" in out
        assert "parity=True" in out
        assert "FusedSegment{" in out


class TestExchangeReport:
    def test_boundary_modes_and_q3_collective_check(self, capsys):
        """tools/exchange_report.py renders one row per fragment
        boundary with its exchange mode, and --check pins TPC-H Q3's
        boundaries lowering to the collective tier."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        exchange_report = importlib.import_module("exchange_report")
        rc = exchange_report.main(["q3", "q6", "--scale", "0.002",
                                   "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "data plane: collective" in out
        assert "hash" in out and "single" in out

    def test_live_per_shard_bytes_and_q3_pin(self, capsys):
        """--live executes on a real mesh and reports per-boundary
        rows/bytes from the program's per-shard telemetry; --check pins
        TPC-H Q3 reporting nonzero device-boundary bytes on EVERY
        collective boundary."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        exchange_report = importlib.import_module("exchange_report")
        rc = exchange_report.main(["q3", "--scale", "0.002", "--live",
                                   "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "live mesh: 2 shards" in out
        assert "bytes/shard" in out
        assert "all_to_all" in out and "gather" in out
        # every rendered boundary row carries a nonzero byte total
        for ln in out.splitlines():
            if ln.strip().startswith("f") and "all_" in ln:
                assert ln.split()[-1].isdigit()

    def test_segments_column_names_boundary_roles(self, capsys):
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        exchange_report = importlib.import_module("exchange_report")
        rc = exchange_report.main(["q3", "--scale", "0.002",
                                   "--segments"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "fed-by-exchange" in out or "feeds-exchange" in out


class TestQpsRun:
    def test_check_mode(self, capsys):
        """tools/qps_run.py --check: the serving-tier CI smoke — a tiny
        closed-loop run at 2 concurrency levels against a live 2-worker
        DQR asserting per-client exact-rows parity, nonzero plan-cache
        hits, and zero jit compiles on the second execution of a cached
        plan — then a hot-repeat run with the result cache on asserting
        nonzero result-cache hits with exact rows."""
        import importlib
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        qps_run = importlib.import_module("qps_run")
        rc = qps_run.main(["--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["check"] == {
            "parity": True, "plan_cache_hits": True,
            "zero_second_run_compiles": True,
            "second_run_plan_cached": True,
            "hot_parity": True, "result_cache_hits": True,
            "result_cache_bytes_served": True,
            "hot_second_run_result_cached": True}
        levels = payload["report"]["levels"]
        assert [lv["concurrency"] for lv in levels] == [1, 2]
        for lv in levels:
            assert lv["qps"] > 0 and lv["p99_ms"] >= lv["p50_ms"]
        # the hot tier really served from the cache
        hot = payload["hot_report"]
        assert hot["result_cache_hit_rate"] > 0.0
        assert hot["result_cache_bytes_served"] > 0


class TestQueryProfile:
    def test_live_profile_check_mode(self, capsys, tmp_path):
        """tools/query_profile.py --check: runs a statement on a real
        2-worker DQR and renders the per-stage stats table + task span
        timeline from the coordinator's rollup."""
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        query_profile = importlib.import_module("query_profile")
        log = str(tmp_path / "query.json")
        rc = query_profile.main(
            ["--scale", "0.002", "--check", "--live",
             "--event-log", log])
        out = capsys.readouterr().out
        assert rc == 0, out
        # the timed span tree replaced the ad-hoc task reconstruction:
        # coordinator phases + per-stage spans render in the timeline
        assert "span timeline" in out
        assert "schedule" in out and "execute" in out
        assert "stage-0" in out
        assert "profile rollup complete" in out
        assert "trace=tt-" in out
        # stage table rendered both fragments with real rows
        assert "xchg f/c/p" in out
        # --live followed the timeseries endpoint
        assert "time series (" in out
        assert "splits q/r/c" in out

        # replay mode renders the log the live run just wrote,
        # including the span tree carried on QueryCompletedEvent
        rc = query_profile.main(["--replay", log])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "QueryCreatedEvent" in out
        assert "QueryCompletedEvent" in out
        assert "stage stats for" in out
        assert "spans for" in out


class TestPerfRegress:
    """tools/perf_regress.py: the bench trajectory as an enforced gate."""

    def _tool(self):
        import importlib
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        return importlib.import_module("perf_regress")

    def _artifact(self, path, headline, extras=()):
        import json

        doc = {"metric": "tpch_sf0.1_q1_rows_per_sec_per_chip",
               "value": headline, "unit": "rows/s",
               "extras": [{"metric": m, "value": v, "unit": "rows/s"}
                          for m, v in extras]}
        path.write_text(json.dumps(doc))
        return str(path)

    def test_committed_pr7_pr8_pair_passes(self, capsys):
        """The acceptance pin: the committed BENCH_PR7 -> BENCH_PR8
        artifact pair is within tolerance (worst matched config is the
        -3.4%% headline), so --check exits 0."""
        import os

        root = os.path.join(os.path.dirname(__file__), "..")
        rc = self._tool().main(
            ["--check",
             os.path.join(root, "BENCH_PR7_20260805.json"),
             os.path.join(root, "BENCH_PR8_20260805.json")])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "no regressions past tolerance" in out
        # configs matched by name, per-config delta reported
        assert "tpch_sf0.1_q1_rows_per_sec_per_chip" in out
        assert "OK" in out

    def test_committed_pr9_pr10_pair_passes(self, capsys):
        """The PR 10 acceptance gate: the committed BENCH_PR9 -> PR10
        pair is green — the engine Q1 config improved >= 2x (the
        device-resident hash tier + scan-dictionary interning), the new
        join-heavy bench_engine_q3q9 config reports NEW (tracked from
        here on), and no matched config regressed past tolerance."""
        import json
        import os

        root = os.path.join(os.path.dirname(__file__), "..")
        rc = self._tool().main(
            ["--check",
             os.path.join(root, "BENCH_PR9_20260805.json"),
             os.path.join(root, "BENCH_PR10_20260805.json")])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "no regressions past tolerance" in out
        assert "tpch_sf0.05_q3_engine_rows_per_sec" in out   # NEW config
        with open(os.path.join(root, "BENCH_PR9_20260805.json")) as f:
            old = json.load(f)
        with open(os.path.join(root, "BENCH_PR10_20260805.json")) as f:
            new = json.load(f)

        def metric(doc, name):
            for e in doc["extras"]:
                if e.get("metric") == name:
                    return e
            return None

        o = metric(old, "tpch_sf0.05_q1_engine_rows_per_sec")
        n = metric(new, "tpch_sf0.05_q1_engine_rows_per_sec")
        assert n["value"] >= 2 * o["value"], (o["value"], n["value"])
        assert n["parity"] is True
        q3q9 = metric(new, "tpch_sf0.05_q3_engine_rows_per_sec")
        assert q3q9 is not None and q3q9["parity"] is True

    def test_injected_regression_fails_check(self, capsys, tmp_path):
        """A synthetic 2x regression on a matched config must fail
        --check; unmatched configs (NEW/DROPPED) never gate."""
        old = self._artifact(tmp_path / "old.json", 1_000_000.0,
                             [("mesh_q1", 300_000.0),
                              ("dropped_only", 42.0)])
        new = self._artifact(tmp_path / "new.json", 980_000.0,
                             [("mesh_q1", 150_000.0),   # 2x regression
                              ("new_only", 7.0)])
        rc = self._tool().main(["--check", old, new])
        out = capsys.readouterr().out
        assert rc == 1, out
        assert "REGRESSED" in out and "mesh_q1" in out
        assert "REGRESSION: 1 config(s)" in out
        assert "NEW" in out and "DROPPED" in out

    def test_within_tolerance_pair_passes(self, capsys, tmp_path):
        old = self._artifact(tmp_path / "a.json", 1_000_000.0,
                             [("mesh_q1", 300_000.0)])
        new = self._artifact(tmp_path / "b.json", 950_000.0,
                             [("mesh_q1", 295_000.0)])
        rc = self._tool().main(["--check", old, new])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "no regressions past tolerance" in out

    def test_tolerance_flag(self, capsys, tmp_path):
        """--tolerance tightens the band: a -5%% drop fails at 2%%."""
        old = self._artifact(tmp_path / "a.json", 1_000_000.0)
        new = self._artifact(tmp_path / "b.json", 950_000.0)
        rc = self._tool().main(["--check", "--tolerance", "0.02",
                                old, new])
        assert rc == 1
        capsys.readouterr()


class TestChaosRunHA:
    def test_ha_check_mode(self, capsys):
        """tools/chaos_run.py --mode ha --check: the coordinator-HA CI
        smoke — kill the PRIMARY COORDINATOR mid-drain of a TPC-DS Q72
        run on a 2-worker HA mesh, headless; nonzero on inexact rows
        through the standby or on any producer re-run for stages
        already complete in the spool."""
        import importlib
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        chaos_run = importlib.import_module("chaos_run")
        rc = chaos_run.main(["--mode", "ha", "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        report = json.loads(out[out.index("{\n"):])
        assert report["mode"] == "ha"
        assert report["phases"] == ["RUNNING"]
        assert report["total_producer_reruns"] == 0
        stage = report["stages"][0]
        assert stage["ok"] and stage["failovers"] == 1
        assert stage["adopted_outcome"] in ("reattached", "repointed",
                                            "restarted")


class TestChaosRunOom:
    def test_oom_check_mode(self, capsys):
        """tools/chaos_run.py --mode oom --check: the memory-arbitration
        CI smoke — a runaway query parks holding ~94% of an 8 MiB worker
        pool, survivors block on the pool, and the low-memory killer
        must fail EXACTLY the runaway with the CLUSTER_OUT_OF_MEMORY
        shape; survivors return exact rows, pools drain to zero, and
        both workers stay alive."""
        import importlib
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        chaos_run = importlib.import_module("chaos_run")
        rc = chaos_run.main(["--mode", "oom", "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        report = json.loads(out[out.index("{\n"):])
        assert report["mode"] == "oom"
        assert report["ok"]
        stages = {s["stage"]: s for s in report["stages"]}
        assert set(stages) == {"runaway-resident", "kill", "survivors",
                               "recovery"}
        kill = stages["kill"]
        assert kill["errorName"] == "CLUSTER_OUT_OF_MEMORY"
        assert kill["errorType"] == "INSUFFICIENT_RESOURCES"
        assert kill["errorCode"] == 0x0002_0004
        # exactly one policy-selected kill, attributed to the default
        # policy — nothing else died
        assert kill["kill_counters"] == {
            "total-reservation-on-blocked-nodes": 1}
        rec = stages["recovery"]
        assert rec["alive"] == 2
        assert rec["pool_reserved_after"] == 0


class TestQpsRunOverload:
    def test_open_loop_check_mode(self, capsys):
        """tools/qps_run.py --open-loop --check: the graceful-degradation
        CI smoke — an open-loop arrival sweep at 1x and 2x the measured
        saturated rate against a bounded-pool dispatcher; past
        saturation every rejection must be the hinted queue-full shape
        (zero unshaped failures) and goodput must hold >= 80% of the
        closed-loop peak."""
        import importlib
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        qps_run = importlib.import_module("qps_run")
        rc = qps_run.main(["--open-loop", "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        report = json.loads(out[out.index("{\n"):])
        assert report["mode"] == "overload"
        assert report["ok"]
        assert report["peak_parity"]
        assert report["dispatcher"] == {"pool_size": 2, "max_queued": 4}
        top = report["levels"][-1]
        assert top["rate_factor"] == 2.0
        assert top["shed"] > 0            # overload actually shed
        assert all(lv["other"] == 0 for lv in report["levels"])
        assert report["shed_total"] >= top["shed"]
        assert report["goodput_ratio_at_max"] >= 0.8
        # sheds are FAST rejections, not queue waits
        assert top["shed_p95_ms"] < 1000.0


class TestChaosRunMesh:
    def test_mesh_check_mode(self, capsys):
        """tools/chaos_run.py --mode mesh --check: the mid-program
        fault-tolerance CI smoke — inject a device-plane fault at EVERY
        checkpoint group of a TPC-H Q3 collective run in turn,
        headless; nonzero on inexact rows, a fault that never fired, a
        kill that never resumed, or ANY re-execution of a checkpointed
        fragment (re-lowered into the resumed program or re-tasked on
        the HTTP plane)."""
        import importlib
        import json
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tools"))
        chaos_run = importlib.import_module("chaos_run")
        rc = chaos_run.main(["--mode", "mesh", "--check"])
        out = capsys.readouterr().out
        assert rc == 0, out
        report = json.loads(out[out.index("{\n"):])
        assert report["mode"] == "mesh"
        assert report["resume_mode"] == "device"
        assert report["ok"]
        assert len(report["stages"]) >= 2
        assert report["total_resumes"] >= len(report["stages"])
        for stage in report["stages"]:
            assert stage["ok"], stage
            assert stage["injections"] >= 1
            assert stage["resumes"] >= 1
            assert stage["resume_modes"] == ["device"]
