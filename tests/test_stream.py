"""Record-decoder + message-stream connector tests (presto-record-decoder
+ presto-kafka/-local-file roles over the DirTransport)."""

import json

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.api import ColumnMetadata
from presto_tpu.connectors.decoder import (
    CsvRowDecoder, JsonRowDecoder, RawRowDecoder, make_decoder,
)
from presto_tpu.connectors.stream import (
    DirTransport, KafkaTransport, MessageStreamConnector,
    StreamTableDescription,
)
from presto_tpu.localrunner import LocalQueryRunner

COLS = [ColumnMetadata("id", T.BIGINT), ColumnMetadata("name", T.VARCHAR),
        ColumnMetadata("score", T.DOUBLE)]


def test_csv_decoder():
    d = CsvRowDecoder(COLS, [None, None, None])
    assert d.decode(b"7,alice,1.5") == (7, "alice", 1.5)
    assert d.decode(b"7,,") == (7, None, None)
    # mapping reorders fields
    d2 = CsvRowDecoder(COLS, ["2", "0", "1"])
    assert d2.decode(b"bob,0.5,9") == (9, "bob", 0.5)
    # undecodable cell -> NULL, not error
    assert d.decode(b"x,alice,z") == (None, "alice", None)


def test_json_decoder_paths():
    cols = COLS + [ColumnMetadata("city", T.VARCHAR)]
    d = JsonRowDecoder(cols, [None, None, None, "address/city"])
    msg = json.dumps({"id": 3, "name": "cy", "score": 2.25,
                      "address": {"city": "springfield"}}).encode()
    assert d.decode(msg) == (3, "cy", 2.25, "springfield")
    assert d.decode(b"not json") is None
    assert d.decode(b"{}") == (None, None, None, None)


def test_raw_decoder():
    import struct

    cols = [ColumnMetadata("a", T.BIGINT), ColumnMetadata("s", T.VARCHAR)]
    d = RawRowDecoder(cols, ["0:8:>q", "8:12"])
    msg = struct.pack(">q", 77) + b"wxyz"
    assert d.decode(msg) == (77, "wxyz")


def test_make_decoder_avro_needs_schema():
    """The avro decoder requires the table description's dataSchema
    (it is no longer gated on an external library)."""
    from presto_tpu.connectors.api import ColumnMetadata
    from presto_tpu.connectors.decoder import make_decoder
    from presto_tpu import types as T

    with pytest.raises(ValueError, match="dataSchema"):
        make_decoder("avro", [ColumnMetadata("a", T.BIGINT)], [None])


def test_kafka_transport_gated():
    with pytest.raises(RuntimeError, match="kafka"):
        KafkaTransport("localhost:9092")


@pytest.fixture()
def stream_runner(tmp_path):
    topic = tmp_path / "events"
    topic.mkdir()
    (topic / "0.msgs").write_bytes(
        b'{"id": 1, "name": "a", "score": 0.5}\n'
        b'{"id": 2, "name": "b", "score": 1.5}\n')
    (topic / "1.msgs").write_bytes(
        b'{"id": 3, "name": "c", "score": 2.5}\n'
        b'not json at all\n')
    desc = StreamTableDescription.from_dict({
        "name": "events", "decoder": "json",
        "columns": [{"name": "id", "type": "bigint"},
                    {"name": "name", "type": "varchar"},
                    {"name": "score", "type": "double"}]})
    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("stream", MessageStreamConnector(
        DirTransport(str(tmp_path)), [desc]))
    return r


def test_stream_sql(stream_runner):
    got = sorted(stream_runner.execute(
        "SELECT id, name, score FROM stream.events WHERE id IS NOT NULL"
    ).rows)
    assert got == [(1, "a", 0.5), (2, "b", 1.5), (3, "c", 2.5)]
    # undecodable message decodes to NULLs but _message is still exposed
    raw = stream_runner.execute(
        "SELECT _partition_id, _offset, _message FROM stream.events "
        "WHERE id IS NULL").rows
    assert raw == [(1, 1, "not json at all")]
    # aggregation over the stream
    agg = stream_runner.execute(
        "SELECT count(*), sum(score) FROM stream.events").rows
    assert agg == [(4, 4.5)]


def test_stream_partitions_as_splits(stream_runner):
    conn = stream_runner.registry.get("stream")
    splits = conn.get_splits(conn.get_table("events"), 8)
    assert [s.info for s in splits] == [0, 1]


class TestAvroDecoder:
    """Avro binary decoding against a writer schema (the
    presto-record-decoder avro module role, decoder/avro/)."""

    @staticmethod
    def _zigzag(n: int) -> bytes:
        u = (n << 1) ^ (n >> 63)
        out = bytearray()
        while True:
            b = u & 0x7F
            u >>= 7
            if u:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    def _encode(self, rows):
        """Hand-encode (id long, name string, price double,
        ok boolean, note union[null,string]) records."""
        import struct

        msgs = []
        for rid, name, price, ok, note in rows:
            b = bytearray()
            b += self._zigzag(rid)
            nb = name.encode()
            b += self._zigzag(len(nb)) + nb
            b += struct.pack("<d", price)
            b += b"\x01" if ok else b"\x00"
            if note is None:
                b += self._zigzag(0)
            else:
                eb = note.encode()
                b += self._zigzag(1) + self._zigzag(len(eb)) + eb
            msgs.append(bytes(b))
        return msgs

    def test_decode_rows(self):
        from presto_tpu.connectors.api import ColumnMetadata
        from presto_tpu.connectors.decoder import make_decoder
        from presto_tpu import types as T

        schema = {"type": "record", "name": "r", "fields": [
            {"name": "id", "type": "long"},
            {"name": "name", "type": "string"},
            {"name": "price", "type": "double"},
            {"name": "ok", "type": "boolean"},
            {"name": "note", "type": ["null", "string"]},
        ]}
        cols = [ColumnMetadata("id", T.BIGINT),
                ColumnMetadata("name", T.VARCHAR),
                ColumnMetadata("price", T.DOUBLE),
                ColumnMetadata("ok", T.BOOLEAN),
                ColumnMetadata("note", T.VARCHAR)]
        dec = make_decoder("avro", cols, [None] * 5, schema=schema)
        rows = [(1, "alpha", 9.5, True, None),
                (-7, "beta", -0.25, False, "hello"),
                (1 << 40, "", 0.0, True, "x")]
        got = [dec.decode(m) for m in self._encode(rows)]
        assert got == rows

    def test_truncated_message_is_null_row(self):
        from presto_tpu.connectors.api import ColumnMetadata
        from presto_tpu.connectors.decoder import make_decoder
        from presto_tpu import types as T

        schema = {"type": "record", "name": "r", "fields": [
            {"name": "id", "type": "long"},
            {"name": "name", "type": "string"}]}
        cols = [ColumnMetadata("id", T.BIGINT),
                ColumnMetadata("name", T.VARCHAR)]
        dec = make_decoder("avro", cols, [None, None], schema=schema)
        assert dec.decode(b"\x02\x10ab") is None  # length past the end

    def test_stream_connector_avro_table(self, tmp_path):
        import struct

        from presto_tpu.connectors.stream import (
            DirTransport, MessageStreamConnector, StreamTableDescription,
        )
        from presto_tpu.localrunner import LocalQueryRunner

        topic = tmp_path / "events"
        topic.mkdir()
        msgs = self._encode([(i, f"n{i}", i * 1.5, i % 2 == 0, None)
                             for i in range(10)])
        (topic / "0.bin").write_bytes(
            b"".join(struct.pack(">I", len(m)) + m for m in msgs))
        desc = StreamTableDescription.from_dict({
            "name": "events", "decoder": "avro",
            "columns": [{"name": "id", "type": "bigint"},
                        {"name": "name", "type": "varchar"},
                        {"name": "price", "type": "double"}],
            "dataSchema": {"type": "record", "name": "r", "fields": [
                {"name": "id", "type": "long"},
                {"name": "name", "type": "string"},
                {"name": "price", "type": "double"},
                {"name": "ok", "type": "boolean"},
                {"name": "note", "type": ["null", "string"]}]},
        })
        conn = MessageStreamConnector(DirTransport(str(tmp_path)), [desc])
        r = LocalQueryRunner.tpch(scale=0.001)
        r.register("kafka", conn)
        rows = r.execute("select id, name, price from kafka.events "
                         "order by id").rows
        assert len(rows) == 10
        assert rows[0] == (0, "n0", 0.0)
        assert rows[9] == (9, "n9", 13.5)
