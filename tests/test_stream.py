"""Record-decoder + message-stream connector tests (presto-record-decoder
+ presto-kafka/-local-file roles over the DirTransport)."""

import json

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.api import ColumnMetadata
from presto_tpu.connectors.decoder import (
    CsvRowDecoder, JsonRowDecoder, RawRowDecoder, make_decoder,
)
from presto_tpu.connectors.stream import (
    DirTransport, KafkaTransport, MessageStreamConnector,
    StreamTableDescription,
)
from presto_tpu.localrunner import LocalQueryRunner

COLS = [ColumnMetadata("id", T.BIGINT), ColumnMetadata("name", T.VARCHAR),
        ColumnMetadata("score", T.DOUBLE)]


def test_csv_decoder():
    d = CsvRowDecoder(COLS, [None, None, None])
    assert d.decode(b"7,alice,1.5") == (7, "alice", 1.5)
    assert d.decode(b"7,,") == (7, None, None)
    # mapping reorders fields
    d2 = CsvRowDecoder(COLS, ["2", "0", "1"])
    assert d2.decode(b"bob,0.5,9") == (9, "bob", 0.5)
    # undecodable cell -> NULL, not error
    assert d.decode(b"x,alice,z") == (None, "alice", None)


def test_json_decoder_paths():
    cols = COLS + [ColumnMetadata("city", T.VARCHAR)]
    d = JsonRowDecoder(cols, [None, None, None, "address/city"])
    msg = json.dumps({"id": 3, "name": "cy", "score": 2.25,
                      "address": {"city": "springfield"}}).encode()
    assert d.decode(msg) == (3, "cy", 2.25, "springfield")
    assert d.decode(b"not json") is None
    assert d.decode(b"{}") == (None, None, None, None)


def test_raw_decoder():
    import struct

    cols = [ColumnMetadata("a", T.BIGINT), ColumnMetadata("s", T.VARCHAR)]
    d = RawRowDecoder(cols, ["0:8:>q", "8:12"])
    msg = struct.pack(">q", 77) + b"wxyz"
    assert d.decode(msg) == (77, "wxyz")


def test_make_decoder_avro_gated():
    with pytest.raises(ValueError, match="avro"):
        make_decoder("avro", COLS, [None] * 3)


def test_kafka_transport_gated():
    with pytest.raises(RuntimeError, match="kafka"):
        KafkaTransport("localhost:9092")


@pytest.fixture()
def stream_runner(tmp_path):
    topic = tmp_path / "events"
    topic.mkdir()
    (topic / "0.msgs").write_bytes(
        b'{"id": 1, "name": "a", "score": 0.5}\n'
        b'{"id": 2, "name": "b", "score": 1.5}\n')
    (topic / "1.msgs").write_bytes(
        b'{"id": 3, "name": "c", "score": 2.5}\n'
        b'not json at all\n')
    desc = StreamTableDescription.from_dict({
        "name": "events", "decoder": "json",
        "columns": [{"name": "id", "type": "bigint"},
                    {"name": "name", "type": "varchar"},
                    {"name": "score", "type": "double"}]})
    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("stream", MessageStreamConnector(
        DirTransport(str(tmp_path)), [desc]))
    return r


def test_stream_sql(stream_runner):
    got = sorted(stream_runner.execute(
        "SELECT id, name, score FROM stream.events WHERE id IS NOT NULL"
    ).rows)
    assert got == [(1, "a", 0.5), (2, "b", 1.5), (3, "c", 2.5)]
    # undecodable message decodes to NULLs but _message is still exposed
    raw = stream_runner.execute(
        "SELECT _partition_id, _offset, _message FROM stream.events "
        "WHERE id IS NULL").rows
    assert raw == [(1, 1, "not json at all")]
    # aggregation over the stream
    agg = stream_runner.execute(
        "SELECT count(*), sum(score) FROM stream.events").rows
    assert agg == [(4, 4.5)]


def test_stream_partitions_as_splits(stream_runner):
    conn = stream_runner.registry.get("stream")
    splits = conn.get_splits(conn.get_table("events"), 8)
    assert [s.info for s in splits] == [0, 1]
