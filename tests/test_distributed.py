"""DistributedQueryRunner tests: real coordinator + workers + HTTP
exchanges in one process, results pinned against LocalQueryRunner.

Mirrors the reference's multi-node in-JVM tier (DistributedQueryRunner
.java:73; TestTpchDistributedQueries pattern): same SQL through the full
distributed path — fragmentation, task scheduling, partitioned/broadcast
exchanges, partial/final aggregation — must equal the single-process
engine."""

import math

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.server.dqr import DistributedQueryRunner

pytestmark = pytest.mark.slow



@pytest.fixture(scope="module")
def cluster():
    dqr = DistributedQueryRunner.tpch(scale=0.01, n_workers=3)
    yield dqr
    dqr.close()


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=0.01)


def norm(rows):
    return [tuple(round(v, 4) if isinstance(v, float) else v for v in r)
            for r in rows]


def assert_same(cluster, local, sql, ordered=True):
    got = norm(cluster.execute(sql).rows)
    want = norm(local.execute(sql).rows)
    if not ordered:
        got, want = sorted(got), sorted(want)
    assert got == want, (sql, got[:5], want[:5])


QUERIES = [
    # scan + global agg (partial/final across workers)
    "select count(*), sum(l_quantity), min(l_orderkey), max(l_orderkey) "
    "from lineitem",
    # grouped agg with hash exchange (TPC-H Q1 shape)
    """select l_returnflag, l_linestatus, sum(l_quantity), count(*),
       avg(l_extendedprice) from lineitem
       where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus
       order by l_returnflag, l_linestatus""",
    # filter/project (Q6 shape)
    """select sum(l_extendedprice * l_discount) from lineitem
       where l_shipdate >= date '1994-01-01'
       and l_shipdate < date '1995-01-01'
       and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    # broadcast join
    """select n_name, count(*) from nation, region
       where n_regionkey = r_regionkey and r_name = 'ASIA'
       group by n_name order by 1""",
    # left join + agg + topn
    """select c_custkey, count(o_orderkey) from customer
       left join orders on c_custkey = o_custkey
       group by c_custkey order by 2 desc, 1 limit 10""",
    # 3-way join + agg + topn (Q3 shape)
    """select l_orderkey, sum(l_extendedprice * (1 - l_discount)) revenue,
       o_orderdate, o_shippriority from customer, orders, lineitem
       where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
       and l_orderkey = o_orderkey
       and o_orderdate < date '1995-03-15'
       and l_shipdate > date '1995-03-15'
       group by l_orderkey, o_orderdate, o_shippriority
       order by revenue desc, o_orderdate limit 10""",
    # distinct
    "select distinct l_returnflag from lineitem order by 1",
    # semi join
    """select count(*) from orders where o_custkey in
       (select c_custkey from customer where c_mktsegment = 'BUILDING')""",
    # union through the cluster
    """select n_regionkey k from nation union
       select r_regionkey from region order by k""",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_distributed_matches_local(cluster, local, sql):
    assert_same(cluster, local, sql)


def test_window_function_distributed(cluster, local):
    sql = """select o_custkey, o_orderkey,
             row_number() over (partition by o_custkey
                                order by o_orderkey) rn
             from orders where o_custkey < 100"""
    assert_same(cluster, local, sql, ordered=False)


def test_failed_query_surfaces_error(cluster):
    from presto_tpu.client import QueryFailed

    with pytest.raises(QueryFailed):
        cluster.execute("select no_such_column from lineitem")


def test_dbapi_cursor(cluster):
    from presto_tpu.client import connect

    conn = connect(cluster.coordinator.uri)
    cur = conn.cursor()
    cur.execute("select count(*) c from region")
    assert cur.description[0][0] == "c"
    assert cur.fetchone() == (5,)
    assert cur.fetchone() is None


def test_failure_detector_excludes_dead_worker():
    dqr = DistributedQueryRunner.tpch(scale=0.001, n_workers=3)
    try:
        nodes_before = dqr.coordinator.nodes.alive_nodes()
        assert len(nodes_before) == 3
        # kill one worker; the heartbeat detector must notice
        dqr.workers[2].close()
        import time

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if len(dqr.coordinator.nodes.alive_nodes()) == 2:
                break
            time.sleep(0.2)
        assert len(dqr.coordinator.nodes.alive_nodes()) == 2
        # queries still run on the surviving nodes
        res = dqr.execute("select count(*) from nation")
        assert res.rows == [(25,)]
    finally:
        dqr.close()


def test_query_resource_observability(cluster):
    """GET /v1/query lists queries with state (QueryResource role)."""
    import json
    import urllib.request

    cluster.execute("select 42")
    with urllib.request.urlopen(
            f"{cluster.coordinator.uri}/v1/query", timeout=10) as resp:
        queries = json.loads(resp.read())
    assert queries and all("state" in q for q in queries)
    done = [q for q in queries if q["state"] == "FINISHED"]
    assert done
    qid = done[0]["queryId"]
    with urllib.request.urlopen(
            f"{cluster.coordinator.uri}/v1/query/{qid}",
            timeout=10) as resp:
        detail = json.loads(resp.read())
    assert detail["queryId"] == qid
    assert "outputRows" in detail


def test_system_runtime_tables_live(cluster):
    """system.runtime over live cluster state (GlobalSystemConnector)."""
    rows = cluster.execute(
        "select node_id, state from system.nodes order by 1").rows
    assert len(rows) == 3
    assert all(state == "ACTIVE" for _, state in rows)
    rows = cluster.execute(
        "select count(*) from system.queries").rows
    assert rows[0][0] >= 1  # at least this query's predecessors


def test_web_ui_served(cluster):
    """Coordinator serves the status page (webapp role)."""
    import urllib.request

    with urllib.request.urlopen(
            f"{cluster.coordinator.uri}/ui", timeout=10) as resp:
        body = resp.read().decode()
    assert resp.status == 200
    assert "tpu-sql cluster" in body and "/v1/query" in body


def test_system_tasks_live(cluster):
    cluster.execute("select count(*) from lineitem")
    rows = cluster.execute("select * from system.tasks").rows
    assert rows, "no tasks reported"
    for task_id, state, query_id, out_rows, wall_ms, peak, _elapsed in rows:
        assert task_id.startswith(query_id)
        assert state in ("RUNNING", "FINISHED", "FAILED", "CANCELED")
        assert out_rows is None or out_rows >= 0
    # the rollup actually flowed: at least one finished task reports
    # output rows and a wall time (TaskStats fed live into
    # system.runtime.tasks)
    done = [r for r in rows if r[1] == "FINISHED"]
    assert any((r[3] or 0) > 0 for r in done), rows
    assert any((r[4] or 0) > 0 for r in done), rows


def test_system_queries_rollup_live(cluster):
    """system.runtime.queries carries the QueryStats rollup columns."""
    cluster.execute("select count(*) from lineitem")
    rows = cluster.execute(
        "select query_id, state, output_rows, wall_s, "
        "stage_retry_rounds, trace_token from system.queries "
        "where state = 'FINISHED'").rows
    assert rows
    qid, state, out_rows, wall_s, retries, token = rows[-1]
    assert out_rows >= 1 and wall_s > 0 and retries == 0
    assert token and token.startswith("tt-")


def test_kill_query_procedure(cluster):
    """CALL system.runtime.kill_query (KillQueryProcedure.java role)."""
    import json
    import time
    import urllib.request

    body = ("select count(*) from lineitem l1, lineitem l2 "
            "where l1.l_orderkey = l2.l_orderkey").encode()
    req = urllib.request.Request(
        cluster.coordinator.uri + "/v1/statement", data=body, method="POST")
    qid = json.loads(urllib.request.urlopen(req, timeout=10).read())["id"]
    assert cluster.execute(
        f"call system.runtime.kill_query('{qid}')").rows == [("killed",)]
    deadline = time.time() + 30
    while time.time() < deadline:
        qs = json.loads(urllib.request.urlopen(
            cluster.coordinator.uri + "/v1/query", timeout=10).read())
        state = next(q["state"] for q in qs if q["queryId"] == qid)
        if state in ("FAILED", "FINISHED"):
            break
        time.sleep(0.5)
    assert state == "FAILED"


def test_kill_unknown_query_fails(cluster):
    import pytest as _pytest

    from presto_tpu.client import QueryFailed

    with _pytest.raises(QueryFailed):
        cluster.execute("call system.runtime.kill_query('nope')")


def test_distributed_explain_analyze(cluster):
    """EXPLAIN ANALYZE over the cluster: per-fragment operator stats
    rolled up from task status (ExplainAnalyzeOperator.java:34 role)."""
    res = cluster.execute(
        "explain analyze select o_orderpriority, count(*) from orders "
        "where o_totalprice > 1000 group by o_orderpriority")
    text = "\n".join(r[0] for r in res.rows)
    assert "Fragment 0" in text and "Fragment 1" in text
    assert "tasks" in text and "wall ms" in text
    # the source fragment ran as multiple tasks and scanned real rows
    import re

    scan_lines = [l for l in text.splitlines() if "TableScan" in l
                  and "=>" not in l]
    assert scan_lines, text
    counts = [int(x) for x in re.findall(r"\s(\d+)\s", scan_lines[0])]
    assert counts and max(counts) > 0, scan_lines
    # the stats rollup renders REAL remote task stats per fragment:
    # jit counters in the operator table, and a per-stage summary line
    # with wall / peak memory / exchange page counters
    assert "jit disp" in text and "prereduce" in text
    stage_lines = [l for l in text.splitlines()
                   if l.strip().startswith("stage:")]
    assert len(stage_lines) >= 2, text            # one per fragment
    assert all("peak memory" in l and "exchange pages" in l
               for l in stage_lines), stage_lines
    # the scan stage moved real rows and nonzero wall
    assert any(re.search(r"wall [0-9.]+ ms", l) for l in stage_lines)
    # query-level rollup footer names peak memory, jit, and the token
    assert "query: peak memory" in text
    assert "trace token: tt-" in text


def test_distributed_explain_analyze_runner_api(cluster):
    """The DQR path (not just raw /v1/statement) renders the same
    rollup, and the detail payload carries StageStats for the query."""
    import json
    import urllib.request

    res = cluster.execute(
        "explain analyze select count(*) from lineitem")
    text = "\n".join(r[0] for r in res.rows)
    assert "stage:" in text and "jit disp" in text
    # the detail payload of the EXPLAIN ANALYZE query itself exposes
    # the per-stage rollup (satellite: /v1/query/{id} observability)
    with urllib.request.urlopen(
            f"{cluster.coordinator.uri}/v1/query", timeout=10) as resp:
        queries = json.loads(resp.read())
    qid = next(q["queryId"] for q in queries
               if "explain analyze select count" in q["query"])
    with urllib.request.urlopen(
            f"{cluster.coordinator.uri}/v1/query/{qid}",
            timeout=10) as resp:
        detail = json.loads(resp.read())
    assert detail["stageRetryRounds"] == 0
    assert detail["recoveryRounds"] == 0
    assert detail["speculations"] == []
    assert detail["traceToken"].startswith("tt-")
    stages = detail["stageStats"]
    assert stages, detail
    # the leaf stage scanned lineitem: rows flowed and a worker
    # reported peak memory
    total_in = sum(st["input_rows"] for st in stages.values())
    assert total_in > 0
    assert any(st["peak_memory_bytes"] > 0 for st in stages.values())
    assert all(st["reporting"] >= 1 for st in stages.values())
    assert detail["queryStats"]["jit_dispatches"] >= 0
    assert detail["queryStats"]["stages"] == len(stages)


def test_union_branches_distribute_round_robin(cluster, local):
    """UNION ALL branches run as their own source fragments with
    round-robin (P3 / arbitrary) output."""
    sql = ("select count(*), sum(x) from ("
           "select o_totalprice x from orders "
           "union all select l_extendedprice x from lineitem)")
    got = cluster.execute(sql).rows
    want = local.execute(sql).rows
    assert got[0][0] == want[0][0]
    assert abs(got[0][1] - want[0][1]) < 1e-4 * abs(want[0][1])
    # plan shape: the branches must be separate 'arbitrary'-output frags
    plan = cluster.execute(
        "explain (type distributed) select count(*) from ("
        "select o_orderkey k from orders "
        "union all select l_orderkey k from lineitem)").rows
    text = "\n".join(r[0] for r in plan)
    assert "arbitrary" in text, text
