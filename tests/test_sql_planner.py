"""Parser + planner coverage: all 22 TPC-H queries must parse and plan
(the reference's plan-shape test tier, sql/planner assertPlan style,
SURVEY §4.1)."""

import pytest

from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.sql.parser import parse_expression, parse_statement
from presto_tpu.sql.lexer import SqlSyntaxError
from presto_tpu.sql.plan import (
    AggregationNode, JoinNode, LimitNode, OutputNode, SemiJoinNode,
    SortNode, format_plan,
)
from presto_tpu.sql.planner import Metadata, Planner, SqlAnalysisError

from tpch_queries import QUERIES


@pytest.fixture(scope="module")
def metadata():
    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.001))
    return Metadata(reg, "tpch")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query_plans(metadata, qnum):
    stmt = parse_statement(QUERIES[qnum])
    plan = Planner(metadata).plan(stmt)
    assert isinstance(plan, OutputNode)
    text = format_plan(plan)
    assert "TableScan" in text


def test_q3_plan_shape(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[3]))
    text = format_plan(plan)
    assert text.count("TableScan") == 3
    assert "Aggregation" in text and "Limit 10" in text


def test_q4_semijoin_shape(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[4]))
    text = format_plan(plan)
    assert "SemiJoin semi" in text


def test_q21_anti_join_and_residual(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[21]))
    text = format_plan(plan)
    assert "SemiJoin semi" in text and "SemiJoin anti" in text


def test_q17_decorrelated_aggregate(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[17]))
    text = format_plan(plan)
    # the correlated avg became a grouped aggregation joined back in
    assert text.count("Aggregation") == 2


def test_errors(metadata):
    with pytest.raises(SqlSyntaxError):
        parse_statement("select from where")
    with pytest.raises(SqlSyntaxError):
        parse_statement("select 1 +")
    with pytest.raises(SqlAnalysisError):
        Planner(metadata).plan(parse_statement("select nope from lineitem"))
    with pytest.raises(SqlAnalysisError):
        Planner(metadata).plan(parse_statement("select * from missing"))
    with pytest.raises(SqlAnalysisError):
        Planner(metadata).plan(
            parse_statement("select l_orderkey, sum(l_quantity) "
                            "from lineitem group by l_partkey"))


def test_parse_expression_roundtrip():
    e = parse_expression("a + b * 2 >= 3 and not (c like 'x%')")
    assert e is not None


def test_order_by_ordinal_and_alias(metadata):
    plan = Planner(metadata).plan(parse_statement(
        "select l_returnflag rf, count(*) c from lineitem "
        "group by l_returnflag order by 2 desc, rf"))
    text = format_plan(plan)
    assert "Sort" in text


class TestFactorCommonDisjunctConjuncts:
    """(A AND A AND X) OR (A AND Y) regression (ADVICE r5): duplicated
    conjuncts historically double-removed in the factoring rewriter and
    raised ValueError; A must hoist once and duplicates collapse."""

    def test_duplicated_common_conjunct_factors_once(self):
        from presto_tpu.sql.planner import (
            factor_common_disjunct_conjuncts, split_conjuncts,
        )

        e = parse_expression(
            "(a = b and a = b and x > 1) or (a = b and y > 2)")
        out = factor_common_disjunct_conjuncts(e)   # pre-fix: ValueError
        conjs = split_conjuncts(out)
        a_eq_b = parse_expression("a = b")
        assert sum(1 for c in conjs if c == a_eq_b) == 1
        assert len(conjs) == 2                      # A, (X OR Y)

    def test_branch_fully_covered_collapses_to_common(self):
        from presto_tpu.sql.planner import (
            factor_common_disjunct_conjuncts, split_conjuncts,
        )

        e = parse_expression("(a = b and a = b) or (a = b and y > 2)")
        out = factor_common_disjunct_conjuncts(e)
        assert split_conjuncts(out) == [parse_expression("a = b")]

    def test_correlated_subquery_with_duplicated_conjuncts(self):
        """End-to-end through the correlated-EXISTS path that invokes
        the factoring rewriter (the q41-class shape)."""
        from presto_tpu.localrunner import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.001)
        got = runner.execute(
            "select count(*) from tpch.customer c where exists ("
            "select 1 from tpch.orders o where "
            "(o.o_custkey = c.c_custkey and o.o_custkey = c.c_custkey "
            "and o.o_totalprice > 1000) or "
            "(o.o_custkey = c.c_custkey and o.o_orderstatus = 'F'))").rows
        want = runner.execute(
            "select count(distinct o_custkey) from tpch.orders "
            "where o_totalprice > 1000 or o_orderstatus = 'F'").rows
        assert got == want


class TestGeneralSubqueryPositions:
    """Subqueries hoisted into channels/markers (ApplyNode +
    semiJoinOutput-symbol design, round 4): EXISTS/IN under OR, scalar
    subqueries nested in arithmetic/CASE/SELECT."""

    @pytest.fixture(scope="class")
    def runner(self):
        from presto_tpu.localrunner import LocalQueryRunner

        return LocalQueryRunner.tpch(scale=0.01)

    def test_scalar_subquery_in_arithmetic(self, runner):
        got = runner.execute(
            "select count(*) from tpch.part p where p.p_retailprice > "
            "1.2 * (select avg(p2.p_retailprice) from tpch.part p2 "
            "where p2.p_type = p.p_type)").rows
        assert got[0][0] > 0

    def test_scalar_subquery_in_case_select(self, runner):
        got = runner.execute(
            "select case when (select count(*) from tpch.region) > 3 "
            "then (select count(*) from tpch.nation) else -1 end").rows
        assert got == [(25,)]

    def test_correlated_scalar_in_select_list(self, runner):
        got = runner.execute(
            "select c_custkey, (select max(o_totalprice) from tpch.orders "
            "o where o.o_custkey = c.c_custkey) from tpch.customer c "
            "order by c_custkey limit 3").rows
        assert len(got) == 3 and got[0][0] == 1

    def test_exists_under_or(self, runner):
        got = runner.execute(
            "select count(*) from tpch.customer c where "
            "exists (select 1 from tpch.orders o where "
            "o.o_custkey = c.c_custkey and o.o_totalprice > 300000) or "
            "exists (select 1 from tpch.orders o where "
            "o.o_custkey = c.c_custkey and o.o_totalprice < 2000)").rows
        want = runner.execute(
            "select count(distinct c_custkey) from tpch.orders, "
            "tpch.customer where o_custkey = c_custkey and "
            "(o_totalprice > 300000 or o_totalprice < 2000)").rows
        assert got == want

    def test_in_subquery_under_or(self, runner):
        got = runner.execute(
            "select count(*) from tpch.customer c where c.c_custkey in "
            "(select o_custkey from tpch.orders where "
            "o_totalprice > 300000) or c.c_nationkey = 3").rows
        lo = runner.execute("select count(*) from tpch.customer "
                            "where c_nationkey = 3").rows
        assert got[0][0] >= lo[0][0]

    def test_parenthesized_setop_derived_table(self, runner):
        got = runner.execute(
            "select count(*) from ( (select r_regionkey k from "
            "tpch.region) intersect select n_regionkey k from "
            "tpch.nation ) t").rows
        assert got == [(5,)]

    def test_not_in_under_or_build_null_3vl(self, runner):
        runner.execute("CREATE TABLE memory.nio_a (x BIGINT, y BIGINT)")
        runner.execute(
            "INSERT INTO memory.nio_a VALUES (1, 0), (2, 1), (3, 0)")
        runner.execute("CREATE TABLE memory.nio_b (n BIGINT)")
        runner.execute("INSERT INTO memory.nio_b VALUES (1), (NULL)")
        got = sorted(x[0] for x in runner.execute(
            "SELECT x FROM memory.nio_a WHERE x NOT IN "
            "(SELECT n FROM memory.nio_b) OR y = 1").rows)
        # NOT IN is UNKNOWN for unmatched x against a NULL-bearing build
        assert got == [2]

    def test_right_full_joins(self, runner):
        rows = sorted(runner.execute(
            "SELECT r.r_name, n.n_name FROM tpch.nation n RIGHT JOIN "
            "tpch.region r ON n.n_regionkey = r.r_regionkey "
            "AND r.r_name = 'ASIA'").rows, key=str)
        assert len({x[0] for x in rows}) == 5       # regions preserved
        assert sum(1 for x in rows if x[1] is not None) == 5
        full = runner.execute(
            "SELECT count(*) FROM tpch.nation n FULL JOIN tpch.region r "
            "ON n.n_regionkey = r.r_regionkey").rows
        assert full == [(25,)]                       # every region matches

    def test_left_join_preserved_side_on_conjunct(self, runner):
        rows = runner.execute(
            "SELECT n.n_name, r.r_name FROM tpch.nation n LEFT JOIN "
            "tpch.region r ON n.n_regionkey = r.r_regionkey "
            "AND n.n_name = 'CHINA'").rows
        assert len(rows) == 25
        assert sum(1 for x in rows if x[1] is not None) == 1

    def test_correlated_count_defaults_zero(self, runner):
        got = runner.execute(
            "SELECT c_custkey, (SELECT count(*) FROM tpch.orders o "
            "WHERE o.o_custkey = c.c_custkey) FROM tpch.customer c").rows
        assert all(x[1] is not None for x in got)
        assert any(x[1] == 0 for x in got)           # 1/3 customers
