"""Parser + planner coverage: all 22 TPC-H queries must parse and plan
(the reference's plan-shape test tier, sql/planner assertPlan style,
SURVEY §4.1)."""

import pytest

from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.sql.parser import parse_expression, parse_statement
from presto_tpu.sql.lexer import SqlSyntaxError
from presto_tpu.sql.plan import (
    AggregationNode, JoinNode, LimitNode, OutputNode, SemiJoinNode,
    SortNode, format_plan,
)
from presto_tpu.sql.planner import Metadata, Planner, SqlAnalysisError

from tpch_queries import QUERIES


@pytest.fixture(scope="module")
def metadata():
    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=0.001))
    return Metadata(reg, "tpch")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query_plans(metadata, qnum):
    stmt = parse_statement(QUERIES[qnum])
    plan = Planner(metadata).plan(stmt)
    assert isinstance(plan, OutputNode)
    text = format_plan(plan)
    assert "TableScan" in text


def test_q3_plan_shape(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[3]))
    text = format_plan(plan)
    assert text.count("TableScan") == 3
    assert "Aggregation" in text and "Limit 10" in text


def test_q4_semijoin_shape(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[4]))
    text = format_plan(plan)
    assert "SemiJoin semi" in text


def test_q21_anti_join_and_residual(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[21]))
    text = format_plan(plan)
    assert "SemiJoin semi" in text and "SemiJoin anti" in text


def test_q17_decorrelated_aggregate(metadata):
    plan = Planner(metadata).plan(parse_statement(QUERIES[17]))
    text = format_plan(plan)
    # the correlated avg became a grouped aggregation joined back in
    assert text.count("Aggregation") == 2


def test_errors(metadata):
    with pytest.raises(SqlSyntaxError):
        parse_statement("select from where")
    with pytest.raises(SqlSyntaxError):
        parse_statement("select 1 +")
    with pytest.raises(SqlAnalysisError):
        Planner(metadata).plan(parse_statement("select nope from lineitem"))
    with pytest.raises(SqlAnalysisError):
        Planner(metadata).plan(parse_statement("select * from missing"))
    with pytest.raises(SqlAnalysisError):
        Planner(metadata).plan(
            parse_statement("select l_orderkey, sum(l_quantity) "
                            "from lineitem group by l_partkey"))


def test_parse_expression_roundtrip():
    e = parse_expression("a + b * 2 >= 3 and not (c like 'x%')")
    assert e is not None


def test_order_by_ordinal_and_alias(metadata):
    plan = Planner(metadata).plan(parse_statement(
        "select l_returnflag rf, count(*) c from lineitem "
        "group by l_returnflag order by 2 desc, rf"))
    text = format_plan(plan)
    assert "Sort" in text
